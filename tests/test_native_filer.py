"""Native C++ filer hot plane trust suite (round-3 VERDICT item 3).

Earns the filer plane the volume plane's level of trust: byte parity
against the python filer on identical inputs, chunked Transfer-Encoding
PUTs (the round-3 S3 streaming regression), percent-encoded path
canonicalization (round-3 ADVICE high), python-mutation invalidation,
SIGKILL-mid-hotlog crash durability, and metadata-event ordering for
absorbed hot-plane writes.

Reference behaviors:
  weed/server/filer_server_handlers_write_autochunk.go:24 (chunked PUT)
  weed/filer/filer_notify.go:20 (metadata events on every mutation)
"""

import os
import signal
import socket
import subprocess
import sys
import time

import pytest
import requests

from seaweedfs_tpu.native import native_available

pytestmark = pytest.mark.skipif(
    not native_available(), reason="native toolchain unavailable")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


@pytest.fixture(scope="module")
def hot_cluster(tmp_path_factory):
    """master + native volume plane + filer WITH the C++ hot plane."""
    from seaweedfs_tpu.pb import rpc
    from seaweedfs_tpu.server.filer import FilerServer
    from seaweedfs_tpu.server.master import MasterServer
    from seaweedfs_tpu.server.volume import VolumeServer

    from tests.test_cli_server import _pick_ports

    mport, vport, fport = _pick_ports(3)
    master = MasterServer(ip="localhost", port=mport, volume_size_limit_mb=64)
    master.start(vacuum_interval=3600)
    vsrv = VolumeServer(
        directories=[str(tmp_path_factory.mktemp("hfvol"))],
        master=f"localhost:{mport}", ip="localhost", port=vport,
        native=True)
    vsrv.start()
    deadline = time.time() + 10
    while time.time() < deadline and not master.topo.nodes:
        time.sleep(0.05)
    assert master.topo.nodes, "volume server did not register"
    fs = FilerServer(ip="localhost", port=fport,
                     master=f"localhost:{mport}",
                     store_dir=str(tmp_path_factory.mktemp("hffiler")),
                     native_volume_plane=vsrv.native_plane)
    fs.start()
    assert fs.hot_plane is not None, "hot plane did not start"
    # native PUTs need a stocked fid lease pool
    deadline = time.time() + 10
    while time.time() < deadline and fs.hot_plane.lease_remaining() == 0:
        time.sleep(0.05)
    assert fs.hot_plane.lease_remaining() > 0, "lease pool never filled"
    yield master, vsrv, fs
    fs.stop()
    vsrv.stop()
    master.stop()
    rpc.reset_channels()


def _native_url(fs, path: str) -> str:
    return f"http://localhost:{fs.port}{path}"


def _admin_url(fs, path: str) -> str:
    return f"http://localhost:{fs.admin_port}{path}"


def test_byte_parity_native_vs_python(hot_cluster):
    """Identical PUT/GET through both planes: bytes, ETag, Content-Type,
    and absorbed store metadata must agree."""
    _, _, fs = hot_cluster
    cases = [
        (b"x", "text/plain"),
        (b"hello hot plane" * 100, "application/octet-stream"),
        (os.urandom(512 * 1024), "image/png"),
        (b"", ""),  # zero-byte object
    ]
    for i, (payload, ctype) in enumerate(cases):
        npath = f"/buckets/parity/n{i}.bin"
        ppath = f"/buckets/parity/p{i}.bin"
        headers = {"Content-Type": ctype} if ctype else {}
        rn = requests.put(_native_url(fs, npath), data=payload,
                          headers=headers, timeout=10)
        rp = requests.put(_admin_url(fs, ppath), data=payload,
                          headers=headers, timeout=10)
        assert rn.status_code in (200, 201), rn.text
        assert rp.status_code in (200, 201), rp.text

        gn = requests.get(_native_url(fs, npath), timeout=10)
        gp = requests.get(_native_url(fs, ppath), timeout=10)
        assert gn.status_code == gp.status_code == 200
        assert gn.content == gp.content == payload
        if ctype and payload:
            assert gn.headers["Content-Type"] == ctype
            assert gp.headers["Content-Type"] == ctype
        # the SAME object must serve the same ETag through either plane
        # (cross-object ETags can differ: the python write path may gzip,
        # and ETags cover the stored bytes — reference behavior)
        if payload:
            fs.hot_sync()
            ga = requests.get(_admin_url(fs, npath), timeout=10)
            assert ga.status_code == 200 and ga.content == payload
            assert gn.headers.get("ETag") == ga.headers.get("ETag"), \
                (gn.headers, ga.headers)

        # absorbed metadata matches the python-plane entry
        fs.hot_sync()
        en = fs.filer.find_entry(npath)
        ep = fs.filer.find_entry(ppath)
        assert sum(c.size for c in en.chunks) == len(payload)
        assert sum(c.size for c in ep.chunks) == len(payload)
        if ctype:
            assert en.attr.mime == ep.attr.mime == ctype


def test_chunked_transfer_encoding_put(hot_cluster):
    """Streaming generator bodies (requests sends Transfer-Encoding:
    chunked) must work against the native plane — the round-3 regression
    broke every anonymous streaming S3 PUT with a 400→500."""
    _, _, fs = hot_cluster
    payload = os.urandom(300 * 1024)

    def gen():
        for i in range(0, len(payload), 32 * 1024):
            yield payload[i:i + 32 * 1024]

    r = requests.put(_native_url(fs, "/buckets/chunked/s.bin"), data=gen(),
                     timeout=10)
    assert r.status_code in (200, 201), r.text
    g = requests.get(_native_url(fs, "/buckets/chunked/s.bin"), timeout=10)
    assert g.status_code == 200 and g.content == payload

    # a chunked PUT that the plane can't serve natively (here: bigger
    # than max_body) must still succeed — the body is consumed, so the
    # plane PROXIES to python instead of 307ing an unreplayable request
    big = os.urandom(6 * 1024 * 1024)  # > max_body (4MB cap)

    def gen_big():
        for i in range(0, len(big), 256 * 1024):
            yield big[i:i + 256 * 1024]

    r = requests.put(_native_url(fs, "/buckets/chunked/big.bin"),
                     data=gen_big(), timeout=30)
    assert r.status_code in (200, 201), (r.status_code, r.text[:200])
    g = requests.get(_native_url(fs, "/buckets/chunked/big.bin"), timeout=30)
    assert g.status_code == 200 and g.content == big

    # chunk extensions and a trailing empty chunk line are legal framing
    with socket.create_connection(("localhost", fs.port), timeout=10) as s:
        body = b"7;ext=1\r\nchunked\r\n3\r\n-ok\r\n0\r\n\r\n"
        s.sendall(b"PUT /buckets/chunked/raw.bin HTTP/1.1\r\n"
                  b"Host: x\r\nTransfer-Encoding: chunked\r\n"
                  b"Connection: close\r\n\r\n" + body)
        resp = b""
        while chunk := s.recv(4096):
            resp += chunk
    assert b" 201 " in resp.split(b"\r\n", 1)[0] + b" ", resp[:200]
    g = requests.get(_native_url(fs, "/buckets/chunked/raw.bin"), timeout=10)
    assert g.content == b"chunked-ok"


def test_percent_encoded_paths_are_canonical(hot_cluster):
    """'/a%20b' and '/a b' are ONE object on both planes (ADVICE high:
    encoded hot-map keys used to diverge from the decoded store path)."""
    _, _, fs = hot_cluster
    enc = "/buckets/pct/a%20b%20c.txt"
    dec = "/buckets/pct/a b c.txt"
    r = requests.put(_native_url(fs, enc), data=b"spaces v1", timeout=10)
    assert r.status_code in (200, 201)

    # native GET by encoded path sees it
    g = requests.get(_native_url(fs, enc), timeout=10)
    assert g.status_code == 200 and g.content == b"spaces v1"

    # absorbed under the DECODED canonical path
    fs.hot_sync()
    e = fs.filer.find_entry(dec)
    assert sum(c.size for c in e.chunks) == len(b"spaces v1")

    # python-plane overwrite must invalidate the hot entry (same key!)
    r = requests.put(_admin_url(fs, enc), data=b"spaces v2 longer",
                     timeout=10)
    assert r.status_code in (200, 201)
    g = requests.get(_native_url(fs, enc), timeout=10)
    assert g.status_code == 200 and g.content == b"spaces v2 longer", \
        "stale hot entry served after python overwrite of encoded path"

    # malformed escapes defer to python (which rejects/normalizes them)
    r = requests.put(_native_url(fs, "/buckets/pct/bad%zz"), data=b"x",
                     timeout=10)
    assert r.status_code != 500


def test_multipart_and_range_served_natively(hot_cluster):
    """Round-3 VERDICT item 8: multipart form uploads and clean byte
    ranges no longer 307 to python (the fast path widened from 67% to
    ~93% on the mixed workload in COVERAGE.md)."""
    _, _, fs = hot_cluster
    before = fs.hot_plane.stats()
    payload = b"multipart native payload" * 10
    r = requests.post(_native_url(fs, "/buckets/wide/mp.bin"),
                      files={"file": ("x.bin", payload)}, timeout=10)
    assert r.status_code == 201, r.text
    g = requests.get(_native_url(fs, "/buckets/wide/mp.bin"), timeout=10)
    assert g.content == payload
    # python semantics: multipart uploads store an empty mime -> GET
    # defaults to application/octet-stream
    assert g.headers["Content-Type"] == "application/octet-stream"

    # clean ranges: lo-hi, lo-, over-long hi clamps; mirror python
    g = requests.get(_native_url(fs, "/buckets/wide/mp.bin"),
                     headers={"Range": "bytes=5-9"}, timeout=10)
    assert g.status_code == 206 and g.content == payload[5:10]
    assert g.headers["Content-Range"] == f"bytes 5-9/{len(payload)}"
    g = requests.get(_native_url(fs, "/buckets/wide/mp.bin"),
                     headers={"Range": "bytes=10-"}, timeout=10)
    assert g.status_code == 206 and g.content == payload[10:]
    g = requests.get(_native_url(fs, "/buckets/wide/mp.bin"),
                     headers={"Range": f"bytes=0-{len(payload) * 2}"},
                     timeout=10)
    assert g.status_code == 206 and g.content == payload
    after = fs.hot_plane.stats()
    assert after["native_puts"] > before["native_puts"]
    assert after["native_gets"] >= before["native_gets"] + 4
    assert after["redirects"] == before["redirects"], \
        "widened requests still redirected to python"

    # unusual forms still defer to python with python's exact semantics
    g = requests.get(_native_url(fs, "/buckets/wide/mp.bin"),
                     headers={"Range": "bytes=-5"}, timeout=10)  # suffix
    assert g.status_code == 206 and g.content == payload[-5:]
    g = requests.get(_native_url(fs, "/buckets/wide/mp.bin"),
                     headers={"Range": f"bytes={len(payload)}-"},
                     timeout=10)
    assert g.status_code == 416  # unsatisfiable: python owns the 416


def test_multipart_boundary_prefix_in_content(hot_cluster):
    """RFC 2046 only forbids the FULL delimiter line in content: a body
    containing CRLF + a prefix of the delimiter ('\\r\\n--bonus' with
    boundary 'b') must not be truncated at the false match."""
    _, _, fs = hot_cluster
    payload = b"head\r\n--bonus bytes that look like a boundary\r\ntail"
    body = (b"--b\r\n"
            b"Content-Disposition: form-data; name=\"file\"; "
            b"filename=\"t.bin\"\r\n\r\n"
            + payload +
            b"\r\n--b--\r\n")
    with socket.create_connection(("localhost", fs.port), timeout=10) as s:
        s.sendall(b"POST /buckets/wide/prefix.bin HTTP/1.1\r\nHost: x\r\n"
                  b"Content-Type: multipart/form-data; boundary=b\r\n"
                  b"Content-Length: " + str(len(body)).encode() +
                  b"\r\nConnection: close\r\n\r\n" + body)
        resp = b""
        while chunk := s.recv(4096):
            resp += chunk
    assert b" 201 " in resp.split(b"\r\n", 1)[0] + b" ", resp[:200]
    g = requests.get(_native_url(fs, "/buckets/wide/prefix.bin"),
                     timeout=10)
    assert g.content == payload, (g.content, payload)


def test_python_delete_invalidates_hot_entry(hot_cluster):
    _, _, fs = hot_cluster
    path = "/buckets/inval/d.txt"
    assert requests.put(_native_url(fs, path), data=b"doomed",
                        timeout=10).status_code in (200, 201)
    fs.hot_sync()
    r = requests.delete(_admin_url(fs, path), timeout=10)
    assert r.status_code in (200, 202, 204)
    g = requests.get(_native_url(fs, path), timeout=10)
    assert g.status_code == 404, \
        f"deleted object still served: {g.status_code}"


def test_metadata_events_ordered(hot_cluster):
    """Subscribers see absorbed hot-plane writes in PUT order
    (filer_notify.go:20 — every mutation emits an event)."""
    _, _, fs = hot_cluster
    fs.hot_sync()
    evs, cursor = fs.filer.read_events(0, timeout=0.1)
    while evs:  # drain the log so only our writes remain
        evs, cursor = fs.filer.read_events(cursor, timeout=0.1)
    paths = [f"/buckets/events/e{i}.txt" for i in range(8)]
    for i, p in enumerate(paths):
        assert requests.put(_native_url(fs, p), data=f"ev{i}".encode(),
                            timeout=10).status_code in (200, 201)
    fs.hot_sync()
    seen: list[str] = []
    deadline = time.time() + 5
    while time.time() < deadline and len(seen) < len(paths):
        evs, cursor = fs.filer.read_events(cursor, timeout=0.5)
        for m in evs:
            ev = m.event_notification
            if ev.new_entry and ev.new_entry.name.startswith("e"):
                seen.append(f"{m.directory}/{ev.new_entry.name}")
    assert [p for p in seen if p in paths] == paths, seen


def test_sigkill_mid_hotlog_preserves_acked_puts(tmp_path):
    """SIGKILL the all-in-one server mid-PUT-storm; every acknowledged
    native PUT must read back after a restart on the same directory (the
    startup path absorbs the crashed plane's hot log before truncating,
    server/filer.py _start_hot_plane)."""
    from tests.test_cli_server import _pick_ports

    port_m, port_v, port_f = _pick_ports(3)
    env = dict(os.environ, SEAWEEDFS_TPU_CODER="native")
    args = [sys.executable, "-m", "seaweedfs_tpu", "server",
            "-dir", str(tmp_path), "-master.port", str(port_m),
            "-volume.port", str(port_v), "-filer",
            "-filer.port", str(port_f)]
    proc = subprocess.Popen(args, env=env, stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL)
    acked: list[tuple[str, bytes]] = []
    try:
        deadline = time.time() + 150
        up = False
        while time.time() < deadline and proc.poll() is None:
            try:
                requests.get(f"http://localhost:{port_f}/", timeout=1)
                up = True
                break
            except requests.RequestException:
                time.sleep(0.3)
        assert up, "all-in-one server did not come up"

        i = 0
        storm_end = time.time() + 4
        while time.time() < storm_end:
            p = f"/buckets/crash/f{i}.bin"
            body = os.urandom(1024) + str(i).encode()
            try:
                r = requests.put(f"http://localhost:{port_f}{p}", data=body,
                                 timeout=5)
            except requests.RequestException:
                break
            if r.status_code in (200, 201):
                acked.append((p, body))
            i += 1
        assert len(acked) > 20, f"storm too small: {len(acked)}"
        os.kill(proc.pid, signal.SIGKILL)
        proc.wait(timeout=10)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10)

    # restart on the same dir; absorbed-from-log entries must all serve
    proc = subprocess.Popen(args, env=env, stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL)
    try:
        deadline = time.time() + 150
        up = False
        while time.time() < deadline and proc.poll() is None:
            try:
                requests.get(f"http://localhost:{port_f}/", timeout=1)
                up = True
                break
            except requests.RequestException:
                time.sleep(0.3)
        assert up, "server did not come back after SIGKILL"
        missing = []
        for p, body in acked:
            g = requests.get(f"http://localhost:{port_f}{p}", timeout=10)
            if g.status_code != 200 or g.content != body:
                missing.append((p, g.status_code))
        assert not missing, \
            f"{len(missing)}/{len(acked)} acked PUTs lost: {missing[:5]}"
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=15)
        except subprocess.TimeoutExpired:
            proc.kill()


def test_corrupt_hotlog_stands_plane_down(tmp_path):
    """A corrupt hot-log record must alarm, halt absorption, AND stop the
    C++ plane from acking PUTs it can no longer make durable (they fall
    back to python and keep working)."""
    from seaweedfs_tpu.pb import rpc
    from seaweedfs_tpu.server.filer import FilerServer
    from seaweedfs_tpu.server.master import MasterServer
    from seaweedfs_tpu.server.volume import VolumeServer
    from tests.test_cli_server import _pick_ports

    mport, vport, fport = _pick_ports(3)
    master = MasterServer(ip="localhost", port=mport, volume_size_limit_mb=64)
    master.start(vacuum_interval=3600)
    vsrv = VolumeServer(directories=[str(tmp_path / "vol")],
                        master=f"localhost:{mport}", ip="localhost",
                        port=vport, native=True)
    vsrv.start()
    deadline = time.time() + 10
    while time.time() < deadline and not master.topo.nodes:
        time.sleep(0.05)
    fs = FilerServer(ip="localhost", port=fport,
                     master=f"localhost:{mport}",
                     store_dir=str(tmp_path / "filer"),
                     native_volume_plane=vsrv.native_plane)
    fs.start()
    try:
        assert fs.hot_plane is not None
        deadline = time.time() + 10
        while time.time() < deadline and fs.hot_plane.lease_remaining() == 0:
            time.sleep(0.05)
        # one good native PUT, absorbed
        assert requests.put(_native_url(fs, "/buckets/c/ok.txt"),
                            data=b"good", timeout=10).status_code == 201
        fs.hot_sync()
        # inject a corrupt record (bad op byte, full header present)
        with open(fs.hot_plane.log_path, "ab") as f:
            f.write(b"\x07" + b"\x00" * 60)
        fs.hot_sync()
        assert fs._hot_log_corrupt
        # plane stood down: PUTs still succeed (via python), and the
        # entry is durably in the store WITHOUT hot-log absorption
        r = requests.put(_native_url(fs, "/buckets/c/after.txt"),
                         data=b"via python", timeout=10)
        assert r.status_code in (200, 201)
        e = fs.filer.find_entry("/buckets/c/after.txt")
        assert sum(c.size for c in e.chunks) == len(b"via python")
        g = requests.get(_native_url(fs, "/buckets/c/after.txt"), timeout=10)
        assert g.status_code == 200 and g.content == b"via python"
    finally:
        fs.stop()
        vsrv.stop()
        master.stop()
        rpc.reset_channels()


def test_native_plane_actually_serves(hot_cluster):
    """The suite above is meaningless if everything 307'd to python:
    assert the C++ plane took real PUT and GET traffic."""
    _, _, fs = hot_cluster
    st = fs.hot_plane.stats()
    assert st["native_puts"] > 10, st
    assert st["native_gets"] > 5, st


def test_high_filer_port_admin_shadow_stays_in_range(tmp_path):
    """A filer on a port where +11000 would pass 65535 must fall back to
    port-11000 for the hot-plane admin listener, like the volume plane
    (volume.py:88) — not crash the whole server with a bind overflow."""
    import socket as _socket

    import pytest as _pytest

    from seaweedfs_tpu.pb import rpc
    from seaweedfs_tpu.server.filer import FilerServer
    from seaweedfs_tpu.server.master import MasterServer
    from seaweedfs_tpu.server.volume import VolumeServer

    from tests.test_cli_server import _pick_ports

    def probe(start: int):
        """Next candidate >= start whose -11000/-10000 shadows are also
        free: a high port whose +11000 shadow overflows. Cheap, so the
        retry loop below re-runs it instead of paying a server startup
        to discover a conflict."""
        for cand in range(start, 65100, 7):
            try:
                with _socket.socket() as s1, _socket.socket() as s2, \
                        _socket.socket() as s3:
                    s1.bind(("", cand))
                    s2.bind(("", cand - 11000))
                    s3.bind(("", cand - 10000))
                return cand  # grpc shadow wraps down (derived_grpc_port)
            except OSError:
                continue
        return None

    mport, vport = _pick_ports(2)
    master = MasterServer(ip="localhost", port=mport,
                          volume_size_limit_mb=64)
    master.start(vacuum_interval=3600)
    vsrv = VolumeServer(directories=[str(tmp_path / "v")],
                        master=f"localhost:{mport}", ip="localhost",
                        port=vport, native=True)
    vsrv.start()
    fs = None
    try:
        deadline = time.time() + 10
        while time.time() < deadline and not master.topo.nodes:
            time.sleep(0.05)
        # probes race concurrent suite tests grabbing ephemeral ports;
        # re-probe + retry across the band rather than flaking
        fport = 60100
        for attempt in range(3):
            fport = probe(fport)
            if fport is None:
                break
            try:
                fs = FilerServer(ip="localhost", port=fport,
                                 master=f"localhost:{mport}",
                                 store_dir=str(tmp_path / f"f{attempt}"),
                                 native_volume_plane=vsrv.native_plane)
                fs.start()
                break
            except OSError:
                if fs is not None:
                    try:
                        fs.stop()
                    except Exception:
                        pass
                fs = None
                fport += 7  # lost the race: next candidate
        if fs is None:
            _pytest.skip("high ports contended by concurrent tests")
        assert fs.admin_port <= 65535
        if fs.hot_plane is not None:
            assert fs.admin_port == fport - 11000
        r = requests.put(f"http://localhost:{fport}/hi/x.bin",
                         data=b"high-port", timeout=20)
        assert r.status_code in (200, 201)
        g = requests.get(f"http://localhost:{fport}/hi/x.bin", timeout=20)
        assert g.status_code == 200 and g.content == b"high-port"
    finally:
        if fs is not None:
            fs.stop()
        vsrv.stop()
        master.stop()
        rpc.reset_channels()


def test_hot_plane_conditional_semantics(hot_cluster):
    """ISSUE-9 review regressions: the hot plane answers If-None-Match
    with the SAME weak entity-tag-list comparison as python, and defers
    every other validator — If-Range above all: a stale validator must
    serve the full 200 (a native 206 would let a client splice new
    bytes onto an old partial download)."""
    _, _, fs = hot_cluster
    payload = b"conditional hot payload" * 64
    path = "/buckets/cond/hot.bin"
    r = requests.put(_native_url(fs, path), data=payload, timeout=10)
    assert r.status_code == 201, r.text
    g = requests.get(_native_url(fs, path), timeout=10)
    assert g.status_code == 200 and g.content == payload
    etag = g.headers["ETag"]

    before = fs.hot_plane.stats()
    # weak + list INM forms 304 natively (not just the exact string)
    for inm in (etag, f"W/{etag}", f'"x", {etag}', "*"):
        g = requests.get(_native_url(fs, path), timeout=10,
                         headers={"If-None-Match": inm})
        assert g.status_code == 304, (inm, g.status_code)
    g = requests.get(_native_url(fs, path), timeout=10,
                     headers={"If-None-Match": '"nope"'})
    assert g.status_code == 200 and g.content == payload
    after = fs.hot_plane.stats()
    assert after["native_gets"] >= before["native_gets"] + 5
    assert after["redirects"] == before["redirects"]

    # If-Range: python owns the decision on BOTH the match and the
    # stale side (the hot plane redirects instead of guessing)
    g = requests.get(_native_url(fs, path), timeout=10,
                     headers={"Range": "bytes=5-9", "If-Range": etag})
    assert g.status_code == 206 and g.content == payload[5:10]
    g = requests.get(_native_url(fs, path), timeout=10,
                     headers={"Range": "bytes=5-9", "If-Range": f"W/{etag}"})
    assert g.status_code == 200 and g.content == payload  # weak: full 200
    g = requests.get(_native_url(fs, path), timeout=10,
                     headers={"Range": "bytes=5-9", "If-Range": '"stale"'})
    assert g.status_code == 200 and g.content == payload
    final = fs.hot_plane.stats()
    assert final["redirects"] >= after["redirects"] + 3


def test_md5_wanting_put_defers_to_python(hot_cluster):
    """ISSUE-9 review regression: a PUT carrying X-Swfs-Want-Md5 (the
    S3 gateway's ETag contract) or Content-MD5 must take the python
    path, which records the whole-body md5 — the hot plane can't, and
    an absorbed crc-etag entry would break PUT-etag revalidation."""
    _, _, fs = hot_cluster
    payload = b"md5 etag contract" * 32
    before = fs.hot_plane.stats()
    r = requests.put(_native_url(fs, "/buckets/md5/want.bin"),
                     data=payload, headers={"X-Swfs-Want-Md5": "1"},
                     timeout=10)
    assert r.status_code in (200, 201), r.text
    after = fs.hot_plane.stats()
    assert after["redirects"] > before["redirects"]
    assert after["native_puts"] == before["native_puts"]
    # the python path recorded the md5: the served ETag is the 32-hex
    # whole-body digest, which a PUT-returned etag revalidates against
    import hashlib
    g = requests.get(_native_url(fs, "/buckets/md5/want.bin"), timeout=10)
    assert g.status_code == 200 and g.content == payload
    md5_etag = f'"{hashlib.md5(payload).hexdigest()}"'
    assert g.headers["ETag"] == md5_etag
    assert requests.get(_native_url(fs, "/buckets/md5/want.bin"),
                        headers={"If-None-Match": md5_etag},
                        timeout=10).status_code == 304
