"""EC dispatch scheduler suite (ISSUE 3): stacked encode/reconstruct
bit-identity, flush-window ordering, clean shutdown, the
reconstructed-interval cache, and the satellites that rode along
(best-effort fallocate, thread-safe .ecx lookups).

The load-bearing property is GOLDEN-OUTPUT SAFETY: with the scheduler on
or off, .ec00-.ec13 bytes are identical — batching is allowed to change
only when dispatches happen, never what they compute.
"""

import os
import threading

import numpy as np
import pytest

from seaweedfs_tpu.models.coder import new_coder
from seaweedfs_tpu.ops import dispatch
from seaweedfs_tpu.ops.rs_cpu import RSCodecCPU
from seaweedfs_tpu.storage import ec_files
from seaweedfs_tpu.storage import ec_volume as ecv
from seaweedfs_tpu.storage.ec_locate import Geometry
from seaweedfs_tpu.utils import stats

TEST_GEO = Geometry(large_block=10000, small_block=100)


@pytest.fixture(autouse=True)
def _clean_schedulers():
    yield
    dispatch.shutdown_all()
    assert not _flusher_threads(), "leaked ec-dispatch flusher thread"


def _flusher_threads():
    return [t for t in threading.enumerate()
            if t.name.startswith("ec-dispatch") and t.is_alive()]


def _make_volume(base, seed=0, n_needles=40):
    import sys

    sys.path.insert(0, os.path.dirname(__file__))
    from test_ec_pipeline import _make_synthetic_volume

    _make_synthetic_volume(base, seed=seed, n_needles=n_needles)


# -- stacked op bit-identity -------------------------------------------------


@pytest.mark.parametrize("backend", ["cpu", "tpu", "single"])
def test_encode_parity_stacked_matches_per_slab(backend):
    coder = new_coder(10, 4, backend)
    oracle = RSCodecCPU(10, 4)
    rng = np.random.default_rng(1)
    stack = rng.integers(0, 256, (5, 10, 777), dtype=np.uint8)
    want = np.stack([np.asarray(oracle.encode_parity(s)) for s in stack])
    got = np.asarray(coder.encode_parity_stacked(stack))
    assert got.shape == (5, 4, 777)
    assert np.array_equal(got, want)


def test_encode_parity_stacked_ragged_zero_padding():
    """Ragged tails ride zero-padded columns; the padding must slice away
    without perturbing real columns (EOF zero-fill / small-row schedule)."""
    coder = new_coder(10, 4, "cpu")
    rng = np.random.default_rng(2)
    widths = [512, 100, 37, 512]
    bmax = max(widths)
    stack = np.zeros((len(widths), 10, bmax), dtype=np.uint8)
    slabs = []
    for i, w in enumerate(widths):
        s = rng.integers(0, 256, (10, w), dtype=np.uint8)
        stack[i, :, :w] = s
        slabs.append(s)
    out = np.asarray(coder.encode_parity_stacked(stack))
    for i, (w, s) in enumerate(zip(widths, slabs)):
        assert np.array_equal(out[i][:, :w],
                              np.asarray(coder.encode_parity(s)))
        assert not out[i][:, w:].any(), "zero columns must encode to zero"


@pytest.mark.parametrize("data_only", [False, True])
def test_reconstruct_stacked_survivor_permutations(data_only):
    """CPU mirror vs device path across unsorted survivor orderings —
    the scheduler keys lanes by the caller's order, so every permutation
    must reconstruct identically."""
    cpu = new_coder(10, 4, "cpu")
    dev = new_coder(10, 4, "tpu")
    rng = np.random.default_rng(3)
    data = rng.integers(0, 256, (10, 333), dtype=np.uint8)
    shards = np.asarray(cpu.encode(
        np.vstack([data, np.zeros((4, 333), np.uint8)])))
    for _ in range(5):
        ids = list(range(14))
        rng.shuffle(ids)
        pres = tuple(ids[:11])
        stk = np.stack([shards[i] for i in pres])
        m1, r1 = cpu.reconstruct_stacked(pres, stk, data_only=data_only)
        m2, r2 = dev.reconstruct_stacked(pres, stk, data_only=data_only)
        assert m1 == tuple(m2)
        assert np.array_equal(np.asarray(r1), np.asarray(r2))
        for j, mid in enumerate(m1):
            assert np.array_equal(np.asarray(r1[j]), shards[mid])


# -- pipeline golden safety: scheduler on vs off -----------------------------


def test_generate_ec_files_bit_identical_scheduler_on_off(
        tmp_path, monkeypatch):
    """The acceptance pin: .ec00-.ec13 bytes identical with the scheduler
    on and off, over a volume whose tail exercises the small-row schedule
    and EOF zero padding."""
    outs = {}
    for mode in ("0", "1"):
        monkeypatch.setenv("SWFS_EC_DISPATCH", mode)
        base = str(tmp_path / f"m{mode}")
        _make_volume(base, seed=11)
        coder = new_coder(10, 4, "tpu")
        ec_files.generate_ec_files(base, coder, TEST_GEO, batch_size=50)
        outs[mode] = [
            open(TEST_GEO.shard_file_name(base, i), "rb").read()
            for i in range(14)
        ]
    for i in range(14):
        assert outs["0"][i] == outs["1"][i], f"shard {i} differs"


def test_rebuild_ec_files_via_scheduler(tmp_path, monkeypatch):
    monkeypatch.setenv("SWFS_EC_DISPATCH", "1")
    base = str(tmp_path / "r")
    _make_volume(base, seed=12)
    coder = new_coder(10, 4, "cpu")
    ec_files.generate_ec_files(base, coder, TEST_GEO, batch_size=100)
    originals = {}
    for i in (1, 6, 12):
        p = TEST_GEO.shard_file_name(base, i)
        originals[i] = open(p, "rb").read()
        os.remove(p)
    rebuilt = ec_files.rebuild_ec_files(base, coder, TEST_GEO,
                                        batch_size=1 << 16)
    assert sorted(rebuilt) == [1, 6, 12]
    for i, want in originals.items():
        assert open(TEST_GEO.shard_file_name(base, i),
                    "rb").read() == want


def test_degraded_read_via_scheduler_matches_direct(tmp_path, monkeypatch):
    """EcVolume._read_interval micro-batch path == the direct dict
    reconstruct, bytes for bytes."""
    base = str(tmp_path / "g")
    _make_volume(base, seed=13)
    coder = new_coder(10, 4, "cpu")
    ec_files.generate_ec_files(base, coder, TEST_GEO, batch_size=100)
    ec_files.write_sorted_file_from_idx(base)
    monkeypatch.setenv("SWFS_EC_DISPATCH", "0")
    vol = ecv.EcVolume(base, coder, TEST_GEO)
    want = {nid: vol.read_needle_blob(nid) for nid in (1, 7, 25)}
    vol.close()
    for i in (0, 3, 9, 12):
        os.remove(TEST_GEO.shard_file_name(base, i))
    monkeypatch.setenv("SWFS_EC_DISPATCH", "1")
    vol = ecv.EcVolume(base, coder, TEST_GEO)
    for nid, blob in want.items():
        assert vol.read_needle_blob(nid) == blob
    vol.close()


# -- scheduler semantics -----------------------------------------------------


def test_flush_window_fifo_ordering_and_batching():
    """Slabs submitted in order from one thread (= one volume's pipeline)
    must resolve to THEIR parity in submission order, and a batch must
    actually form (the whole point)."""
    coder = RSCodecCPU(10, 4)
    sched = dispatch.EcDispatchScheduler(coder, window=0.25)
    try:
        rng = np.random.default_rng(4)
        slabs = [rng.integers(0, 256, (10, 64 + 8 * i), dtype=np.uint8)
                 for i in range(6)]
        b0 = stats.EC_DISPATCH_BATCHES.value(lane="encode")
        futs = [sched.encode_parity(s) for s in slabs]
        for s, f in zip(slabs, futs):
            assert np.array_equal(np.asarray(f),
                                  np.asarray(coder.encode_parity(s)))
        b1 = stats.EC_DISPATCH_BATCHES.value(lane="encode")
        assert b1 - b0 < len(slabs), "no batching happened"
    finally:
        sched.close()


def test_scheduler_demand_flush_no_window_stall():
    """A consumer blocking on a pending future must not wait out a long
    window — demand flush dispatches immediately."""
    import time

    coder = RSCodecCPU(10, 4)
    sched = dispatch.EcDispatchScheduler(coder, window=30.0)
    try:
        data = np.arange(640, dtype=np.uint8).reshape(10, 64)
        t0 = time.perf_counter()
        fut = sched.encode_parity(data)
        out = np.asarray(fut.result(timeout=10))
        assert time.perf_counter() - t0 < 5.0
        assert np.array_equal(out, np.asarray(coder.encode_parity(data)))
    finally:
        sched.close()


def test_scheduler_clean_shutdown_joins_flusher():
    coder = RSCodecCPU(10, 4)
    sched = dispatch.scheduler_for(coder)
    fut = sched.encode_parity(
        np.zeros((10, 32), dtype=np.uint8))
    np.asarray(fut)
    assert _flusher_threads() or True  # may have idled out already
    sched.close()
    assert sched.closed
    for t in _flusher_threads():
        t.join(timeout=2)
    assert not _flusher_threads()
    # a closed scheduler refuses work; scheduler_for hands out a fresh one
    with pytest.raises(RuntimeError):
        sched.encode_parity(np.zeros((10, 8), np.uint8))
    again = dispatch.scheduler_for(coder)
    assert again is not sched and not again.closed
    again.close()


def test_scheduler_error_propagates_to_futures():
    class Broken:
        data_shards, parity_shards, total_shards = 10, 4, 14

        def encode_parity(self, data):
            raise IOError("boom")

        def encode_parity_stacked(self, stack):
            raise IOError("boom")

    sched = dispatch.EcDispatchScheduler(Broken(), window=0.01)
    try:
        fut = sched.encode_parity(np.zeros((10, 16), np.uint8))
        with pytest.raises(IOError):
            fut.result(timeout=5)
    finally:
        sched.close()


def test_dispatch_env_gate(monkeypatch):
    coder = RSCodecCPU(10, 4)
    monkeypatch.setenv("SWFS_EC_DISPATCH", "0")
    assert dispatch.maybe_scheduler(coder) is None
    monkeypatch.setenv("SWFS_EC_DISPATCH", "1")
    sched = dispatch.maybe_scheduler(coder)
    assert sched is not None
    sched.close()


# -- reconstructed-interval cache -------------------------------------------


def test_recon_cache_lru_bound_and_invalidate():
    cache = dispatch.ReconstructIntervalCache(max_bytes=1000,
                                              block_size=100)
    for blk in range(8):
        cache.put(7, 3, blk, b"x" * 200)  # 8 * 200 > 1000 -> evictions
    assert len(cache) <= 5
    assert cache.get(7, 3, 7) == b"x" * 200  # newest survives
    assert cache.get(7, 3, 0) is None  # oldest evicted
    cache.put(8, 1, 0, b"y" * 100)
    assert cache.invalidate(7) > 0
    assert cache.get(7, 3, 7) is None
    assert cache.get(8, 1, 0) == b"y" * 100  # other volumes untouched
    assert cache.invalidate(8) == 1
    assert len(cache) == 0


def test_recon_cache_block_math():
    cache = dispatch.ReconstructIntervalCache(max_bytes=1 << 20,
                                              block_size=100)
    assert list(cache.blocks_for(0, 1)) == [0]
    assert list(cache.blocks_for(99, 2)) == [0, 1]
    assert list(cache.blocks_for(250, 100)) == [2, 3]
    assert list(cache.blocks_for(0, 0)) == []


def test_recon_cache_generation_guards_stale_put():
    """A reconstruct that straddles an invalidate (shard remount while
    the k-survivor gather is in flight) must not repopulate the cache
    with pre-invalidation bytes."""
    cache = dispatch.ReconstructIntervalCache(max_bytes=1 << 20,
                                              block_size=100)
    gen = cache.generation(7)  # snapshot before "reading survivors"
    cache.invalidate(7)  # remount lands mid-reconstruct
    cache.put(7, 1, 0, b"stale", gen=gen)
    assert cache.get(7, 1, 0) is None, "stale put survived the remount"
    gen2 = cache.generation(7)
    assert gen2 != gen
    cache.put(7, 1, 0, b"fresh", gen=gen2)
    assert cache.get(7, 1, 0) == b"fresh"


def test_recon_cache_disabled_by_zero_budget():
    cache = dispatch.ReconstructIntervalCache(max_bytes=0)
    assert not cache.enabled()
    cache.put(1, 1, 0, b"z")
    assert len(cache) == 0


# -- satellites --------------------------------------------------------------


def test_fallocate_best_effort_per_file(tmp_path, monkeypatch):
    """One shard file's failed preallocation must not strip it from the
    rest (the old loop `break`-ed on the first OSError)."""
    if not hasattr(os, "posix_fallocate"):
        pytest.skip("no posix_fallocate on this platform")
    base = str(tmp_path / "f")
    _make_volume(base, seed=14)
    calls = []
    real = os.posix_fallocate

    def flaky(fd, offset, length):
        calls.append(fd)
        if len(calls) == 3:  # third shard file fails
            raise OSError(95, "fallocate unsupported here")
        return real(fd, offset, length)

    monkeypatch.setattr(os, "posix_fallocate", flaky)
    coder = new_coder(10, 4, "cpu")
    ec_files.generate_ec_files(base, coder, TEST_GEO, batch_size=100)
    assert len(calls) == 14, "preallocation stopped at the first failure"
    want = TEST_GEO.shard_size(os.path.getsize(base + ".dat"))
    for i in range(14):
        assert os.path.getsize(TEST_GEO.shard_file_name(base, i)) == want


def test_concurrent_ecx_lookups_are_threadsafe(tmp_path):
    """Regression for the shared-handle seek+read race: N threads binary-
    searching one EcVolume's .ecx concurrently corrupted each other's
    file position and raised spurious NotFoundError (found by the ISSUE-3
    degraded-read probe; fixed with positional pread)."""
    base = str(tmp_path / "c")
    _make_volume(base, seed=15, n_needles=30)
    coder = new_coder(10, 4, "cpu")
    ec_files.generate_ec_files(base, coder, TEST_GEO, batch_size=100)
    ec_files.write_sorted_file_from_idx(base)
    vol = ecv.EcVolume(base, coder, TEST_GEO)
    errs = []
    barrier = threading.Barrier(8)

    def lookup():
        try:
            barrier.wait()
            for _ in range(40):
                for nid in range(1, 31):
                    vol.find_needle(nid)
        except BaseException as e:
            errs.append(e)

    ths = [threading.Thread(target=lookup) for _ in range(8)]
    for t in ths:
        t.start()
    for t in ths:
        t.join()
    vol.close()
    assert not errs, errs[0]
