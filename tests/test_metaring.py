"""Metadata-ring math (ISSUE 19): bounded churn, cross-process
stability, and a golden layout pin.

The whole point of deriving virtual-node positions from BLAKE2b instead
of carrying them on the wire is that every process, every epoch, every
release computes the IDENTICAL layout from (shards, replicas). These
tests make that contract load-bearing:

  * adding/removing one shard moves only a bounded key fraction, and
    every moved key moves to/from the changed shard (consistent
    hashing's defining property — no full reshuffle);
  * a subprocess derives the same routing table (Python hash() is
    salted per process; blake2b is not);
  * a golden layout pins partition assignment so it can never silently
    change between releases.
"""

from __future__ import annotations

import json
import subprocess
import sys

import pytest

from seaweedfs_tpu.cluster.metaring import (
    EPOCH_HEADER,
    WRONG_SHARD_STATUS,
    MetaRing,
    WrongShardError,
    hash64,
    normalize,
    parent_of,
)

SHARDS = [f"localhost:888{i}" for i in range(1, 5)]
KEYS = [f"/dir{i // 16}/sub{i % 16}" for i in range(4096)]


# -- golden pins ------------------------------------------------------------

def test_hash64_golden():
    # BLAKE2b first-8-bytes big-endian: pinned so the ring position of
    # every key is a release-stable fact, not an implementation detail
    assert hash64("/") == 13778807214825741712
    assert hash64("/buckets") == 12148721251896476896
    assert hash64("/a/b") == 15240591694024102120
    assert hash64("/deep/path/x") == 17595502606140747828


def test_golden_ring_layout():
    ring = MetaRing(SHARDS, epoch=7, replicas=8)
    golden = {
        "/": "localhost:8882",
        "/a": "localhost:8884",
        "/a/b": "localhost:8881",
        "/a/b/c": "localhost:8883",
        "/buckets/b1": "localhost:8883",
        "/buckets/b2": "localhost:8883",
        "/deep/p0": "localhost:8884",
        "/deep/p1": "localhost:8884",
        "/deep/p2": "localhost:8881",
        "/deep/p3": "localhost:8881",
        "/deep/p4": "localhost:8883",
        "/x": "localhost:8881",
        "/y": "localhost:8883",
        "/z": "localhost:8881",
        "/tmp/scratch": "localhost:8881",
        "/logs/2026/08/07": "localhost:8882",
    }
    assert {k: ring.shard_for_key(k) for k in golden} == golden


def test_routing_stable_across_processes():
    """A fresh interpreter derives the identical routing table — the
    property Python's salted hash() would silently break."""
    keys = KEYS[:64]
    prog = (
        "import json,sys\n"
        "from seaweedfs_tpu.cluster.metaring import MetaRing\n"
        f"ring = MetaRing({SHARDS!r}, epoch=1, replicas=16)\n"
        f"print(json.dumps([ring.shard_for_key(k) for k in {keys!r}]))\n"
    )
    out = subprocess.run([sys.executable, "-c", prog], check=True,
                         capture_output=True, text=True).stdout
    ring = MetaRing(SHARDS, epoch=1, replicas=16)
    assert json.loads(out) == [ring.shard_for_key(k) for k in keys]


# -- bounded churn ----------------------------------------------------------

def test_add_shard_moves_only_to_new_shard():
    before = MetaRing(SHARDS, epoch=1, replicas=64)
    after = before.with_shard("localhost:8885")
    assert after.epoch == 2
    moved = 0
    for k in KEYS:
        a, b = before.shard_for_key(k), after.shard_for_key(k)
        if a != b:
            moved += 1
            # every moved key lands ON the new shard — an old shard
            # never inherits keys from another old shard
            assert b == "localhost:8885", (k, a, b)
    # expected move fraction is 1/5; anything near a full reshuffle
    # (4/5) means the hash/ring layout broke
    assert 0 < moved / len(KEYS) < 0.40


def test_remove_shard_moves_only_from_removed_shard():
    before = MetaRing(SHARDS, epoch=3, replicas=64)
    gone = SHARDS[2]
    after = before.without_shard(gone)
    assert after.epoch == 4
    assert gone not in after.shards
    moved = 0
    for k in KEYS:
        a, b = before.shard_for_key(k), after.shard_for_key(k)
        if a != b:
            moved += 1
            # only the removed shard's keys move; everyone else's
            # assignment is untouched
            assert a == gone, (k, a, b)
    assert 0 < moved / len(KEYS) < 0.45


def test_membership_not_construction_order_defines_layout():
    a = MetaRing(SHARDS, epoch=5)
    b = MetaRing(list(reversed(SHARDS)), epoch=5)
    assert a == b
    assert all(a.shard_for_key(k) == b.shard_for_key(k)
               for k in KEYS[:256])


def test_rejoin_restores_identical_positions():
    """A crashed shard that rejoins resumes the SAME ring position —
    the property that lets the crash drill route consistently across a
    kill/restart without reshuffling the namespace."""
    ring = MetaRing(SHARDS, epoch=1)
    bounced = ring.without_shard(SHARDS[0]).with_shard(SHARDS[0])
    assert bounced.shards == ring.shards
    assert all(ring.shard_for_key(k) == bounced.shard_for_key(k)
               for k in KEYS[:256])


# -- routing keys -----------------------------------------------------------

def test_entry_routes_by_parent_directory():
    ring = MetaRing(SHARDS, replicas=32)
    for d in ("/a/b", "/deep/x/y/z"):
        owner = ring.shard_for_directory(d)
        # every child entry of d routes with d's key: one shard serves
        # the whole listing, children can never straddle a boundary
        for name in ("f1", "f2", "sub", "weird name.txt"):
            assert ring.shard_for_entry(f"{d}/{name}") == owner


def test_single_and_empty_ring_degenerate():
    assert MetaRing([]).shard_for_key("/x") == ""
    one = MetaRing(["localhost:8888"])
    assert one.shard_for_key("/anything") == "localhost:8888"
    # <=1 shard: everyone owns everything (zero behavior change for
    # unsharded deployments)
    assert one.owns_entry("localhost:8888", "/a/b")
    assert one.owns_entry("some-other-filer", "/a/b")
    assert MetaRing([]).owns_directory("anyone", "/d")


def test_normalize_and_parent():
    assert normalize("a//b/") == "/a/b"
    assert normalize("/") == "/"
    assert parent_of("/a/b/c") == "/a/b"
    assert parent_of("/a") == "/"
    assert parent_of("/") == "/"


# -- pb bridge + wrong-shard protocol ---------------------------------------

def test_pb_roundtrip():
    from seaweedfs_tpu.pb import meta_ring_pb2

    ring = MetaRing(SHARDS, epoch=9, replicas=16)
    resp = meta_ring_pb2.MetaRingResponse()
    ring.fill_response(resp)
    back = MetaRing.from_response(resp)
    assert back == ring
    assert back.shard_for_key("/a/b") == ring.shard_for_key("/a/b")


def test_wrong_shard_error_details_roundtrip():
    e = WrongShardError(12, "localhost:8883")
    parsed = WrongShardError.from_details(str(e))
    assert parsed is not None
    assert (parsed.epoch, parsed.owner) == (12, "localhost:8883")
    # unrelated gRPC details parse to None, not a bogus wrong-shard
    assert WrongShardError.from_details("deadline exceeded") is None
    assert WrongShardError.from_details("") is None
    assert WRONG_SHARD_STATUS == 410
    assert EPOCH_HEADER == "X-Swfs-Ring-Epoch"


# -- MetaRingClient ---------------------------------------------------------

def _client(ring, ttl=60.0):
    from seaweedfs_tpu.wdclient import MetaRingClient

    c = MetaRingClient(filer_grpc="unused:0", ttl=ttl)
    c._ring = ring
    c._expires = 1e18  # cache pinned: tests drive invalidation by hand
    return c


def test_client_note_epoch_invalidates_only_forward():
    ring = MetaRing(SHARDS, epoch=5)
    c = _client(ring)
    assert not c.note_epoch(4)  # lagging 410: cache stays
    assert not c.note_epoch(5)
    assert c.note_epoch(6)      # newer epoch observed: cache dropped
    assert c._expires == 0.0


def test_client_call_routed_stale_retry(monkeypatch):
    old = MetaRing(SHARDS, epoch=1)
    new = old.with_shard("localhost:8885")
    c = _client(old)
    key = next(k for k in KEYS
               if new.shard_for_key(k) != old.shard_for_key(k))
    fetched = []
    monkeypatch.setattr(
        c, "_fetch", lambda trigger: fetched.append(trigger) or new)
    calls = []

    def fn(addr):
        calls.append(addr)
        if len(calls) == 1:  # the shard answers 410 + its newer epoch
            raise WrongShardError(new.epoch, new.shard_for_key(key))
        return addr

    assert c.call_routed(key, fn, directory=True) \
        == new.shard_for_key(key)
    assert calls == [old.shard_for_key(key), new.shard_for_key(key)]
    assert fetched == ["stale"]  # exactly one refresh, exactly one retry


def test_client_call_routed_gives_up_after_one_retry(monkeypatch):
    ring = MetaRing(SHARDS, epoch=3)
    c = _client(ring)
    monkeypatch.setattr(c, "_fetch", lambda trigger: ring)

    def always_wrong(addr):
        raise WrongShardError(3, "localhost:9999")

    with pytest.raises(WrongShardError):
        c.call_routed("/a/b/c", always_wrong)
