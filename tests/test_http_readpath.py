"""HTTPS data plane + zero-copy hot read path (ISSUE 9).

Three layers of coverage:

  * conditional-request conformance units — the RFC 7232/7233 decision
    functions in utils/http.py (entity-tag list parsing, weak-vs-strong
    comparison, If-None-Match precedence, If-Range validators);
  * wdclient keep-alive pool units — hit/miss/evict/expired accounting,
    LIFO reuse, the stale-reuse retry (a server reaping an idle pooled
    connection must cost one transparent redial, never an error), and
    the SWFS_HTTP_POOL=0 escape hatch;
  * the read-path IDENTITY suite — the acceptance criterion that bytes
    served over plain HTTP (native sendfile AND native buffered AND the
    python fallback), over HTTPS, via range-reassembly, and for needles
    still inside the group-commit buffer window are hash-identical.
"""

from __future__ import annotations

import hashlib
import os
import socket
import threading
import time

import pytest
import requests

from seaweedfs_tpu.utils.http import (
    not_modified,
    parse_etag_list,
    range_applies,
    strong_etag_match,
    url_for,
    weak_etag_match,
)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def _sha(b) -> str:
    return hashlib.sha256(bytes(b)).hexdigest()


# -- RFC 7232/7233 conformance units ----------------------------------------


def test_parse_etag_list_forms():
    assert parse_etag_list('"abc"') == ['"abc"']
    assert parse_etag_list('"a", "b" , "c"') == ['"a"', '"b"', '"c"']
    assert parse_etag_list('W/"a", "b"') == ['W/"a"', '"b"']
    assert parse_etag_list("*") == ["*"]
    assert parse_etag_list('"a", *') == ["*"]
    # lenient bare tokens (clients that send unquoted md5s)
    assert parse_etag_list("deadbeef") == ["deadbeef"]
    assert parse_etag_list("a, b") == ["a", "b"]
    # unterminated quote: taken verbatim, never raises
    assert parse_etag_list('"abc') == ['"abc']
    assert parse_etag_list("") == []


def test_weak_vs_strong_comparison():
    assert weak_etag_match('W/"x"', '"x"')
    assert weak_etag_match('"x"', 'W/"x"')
    assert weak_etag_match('"x"', '"x"')
    assert not weak_etag_match('"x"', '"y"')
    assert strong_etag_match('"x"', '"x"')
    assert not strong_etag_match('W/"x"', '"x"')
    assert not strong_etag_match('"x"', 'W/"x"')
    assert not strong_etag_match('W/"x"', 'W/"x"')


def test_not_modified_precedence_and_weak_list():
    etag, mtime = '"abc"', 1700000000
    fresh = time.strftime("%a, %d %b %Y %H:%M:%S GMT",
                          time.gmtime(mtime + 100))
    # If-None-Match list, weak comparison
    assert not_modified({"If-None-Match": 'W/"abc"'}, etag, mtime)
    assert not_modified({"If-None-Match": '"zzz", "abc"'}, etag, mtime)
    assert not_modified({"If-None-Match": "*"}, etag, mtime)
    # §3.3 precedence: a MISSING If-None-Match falls to If-Modified-Since;
    # a PRESENT non-matching one wins over a fresh date
    assert not not_modified(
        {"If-None-Match": '"zzz"', "If-Modified-Since": fresh},
        etag, mtime)
    assert not_modified({"If-Modified-Since": fresh}, etag, mtime)
    assert not not_modified({"If-Modified-Since": "not a date"},
                            etag, mtime)


def test_range_applies_validators():
    etag, mtime = '"abc"', 1700000000
    lm = time.strftime("%a, %d %b %Y %H:%M:%S GMT", time.gmtime(mtime))
    later = time.strftime("%a, %d %b %Y %H:%M:%S GMT",
                          time.gmtime(mtime + 60))
    assert range_applies({}, etag, mtime)  # no If-Range -> honor Range
    assert range_applies({"If-Range": '"abc"'}, etag, mtime)
    # a weak entity-tag NEVER matches If-Range (strong-only comparison)
    assert not range_applies({"If-Range": 'W/"abc"'}, etag, mtime)
    assert not range_applies({"If-Range": '"old"'}, etag, mtime)
    # date validator: exact Last-Modified equality only
    assert range_applies({"If-Range": lm}, etag, mtime)
    assert not range_applies({"If-Range": later}, etag, mtime)
    assert not range_applies({"If-Range": "garbage"}, etag, mtime)


def test_parse_range_zero_length_representation():
    """Review regression: every range against a zero-length body is
    unsatisfiable (416) — a suffix form must not produce the empty
    (0, 0) span, whose Content-Range would render 'bytes 0--1/0'."""
    from seaweedfs_tpu.utils.http import parse_range

    assert parse_range("bytes=-5", 0) == "invalid"
    assert parse_range("bytes=0-", 0) == "invalid"
    assert parse_range("bytes=0-4", 0) == "invalid"
    # non-empty bodies keep the normal suffix clamp
    assert parse_range("bytes=-5", 3) == (0, 3)
    assert parse_range("bytes=-2", 10) == (8, 10)


def test_url_for_scheme_follows_gate(monkeypatch):
    monkeypatch.delenv("SWFS_HTTPS", raising=False)
    assert url_for("h:1", "a/b") == "http://h:1/a/b"
    monkeypatch.setenv("SWFS_HTTPS", "1")
    assert url_for("h:1", "/a") == "https://h:1/a"
    monkeypatch.setenv("SWFS_HTTPS", "0")
    assert url_for("h:1") == "http://h:1"


# -- wdclient keep-alive pool -----------------------------------------------


class _Echo:
    """Tiny threaded HTTP server: /n -> body 'resp-<n>'; remembers the
    client ports it served (distinct port == distinct connection)."""

    def __init__(self, port=None):
        from http.server import BaseHTTPRequestHandler

        from seaweedfs_tpu.utils.httpd import TunedThreadingHTTPServer

        seen = self.client_ports = []

        class H(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"  # like every real swfs plane

            def do_GET(self):
                seen.append(self.client_address[1])
                body = f"resp-{self.path[1:]}".encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        self.srv = TunedThreadingHTTPServer(("", port or 0), H)
        self.port = self.srv.server_address[1]
        threading.Thread(target=self.srv.serve_forever,
                         daemon=True).start()

    def stop(self):
        self.srv.shutdown()
        self.srv.server_close()


@pytest.fixture
def fresh_pool(monkeypatch):
    from seaweedfs_tpu.wdclient.pool import HttpPool

    monkeypatch.delenv("SWFS_HTTP_POOL", raising=False)
    monkeypatch.delenv("SWFS_HTTPS", raising=False)
    return HttpPool()


def test_pool_reuses_connection(fresh_pool):
    srv = _Echo()
    try:
        for i in range(5):
            r = fresh_pool.get(f"http://localhost:{srv.port}/{i}")
            assert r.status == 200 and r.data == f"resp-{i}".encode()
        # one TCP connection end to end
        assert len(set(srv.client_ports)) == 1
    finally:
        srv.stop()


def test_pool_disabled_dials_fresh(fresh_pool, monkeypatch):
    monkeypatch.setenv("SWFS_HTTP_POOL", "0")
    srv = _Echo()
    try:
        for i in range(3):
            assert fresh_pool.get(
                f"http://localhost:{srv.port}/{i}").status == 200
        assert len(set(srv.client_ports)) == 3
    finally:
        srv.stop()


def test_pool_idle_expiry_and_bound(fresh_pool, monkeypatch):
    monkeypatch.setenv("SWFS_HTTP_POOL_IDLE_S", "0.05")
    srv = _Echo()
    try:
        assert fresh_pool.get(f"http://localhost:{srv.port}/a").status \
            == 200
        time.sleep(0.1)  # idle past the TTL: reaped at next checkout
        assert fresh_pool.get(f"http://localhost:{srv.port}/b").status \
            == 200
        assert len(set(srv.client_ports)) == 2
        # bound: the idle set never exceeds SWFS_HTTP_POOL_SIZE
        monkeypatch.setenv("SWFS_HTTP_POOL_SIZE", "1")
        key = ("http", "localhost", srv.port)
        c1, _ = fresh_pool._checkout(key, 5)
        c2, _ = fresh_pool._checkout(key, 5)
        fresh_pool._checkin(key, c1)
        fresh_pool._checkin(key, c2)  # over the bound: evicted (closed)
        assert len(fresh_pool._idle[key]) == 1
    finally:
        srv.stop()


def test_pool_stale_reuse_retries_once(fresh_pool):
    """A pooled connection the server reaped while idle must redial
    transparently; the caller never sees the dead socket — even with
    SEVERAL stale connections pooled to the same host (the retry dials
    fresh instead of drawing another reaped socket)."""
    srv = _Echo()
    port = srv.port
    key = ("http", "localhost", port)
    # pool TWO live connections to the same server
    c1, _ = fresh_pool._checkout(key, 5)
    c1.request("GET", "/a")
    c1.getresponse().read()
    c2, _ = fresh_pool._checkout(key, 5)
    c2.request("GET", "/b")
    c2.getresponse().read()
    fresh_pool._checkin(key, c1)
    fresh_pool._checkin(key, c2)
    srv.stop()  # kills BOTH pooled connections server-side
    srv2 = _Echo(port=port)  # same address, fresh listener
    try:
        r = fresh_pool.get(f"http://localhost:{port}/y", timeout=10)
        assert r.status == 200 and r.data == b"resp-y"
    finally:
        srv2.stop()


def test_pool_fresh_connection_failure_propagates(fresh_pool):
    port = _free_port()  # nothing listening
    with pytest.raises(OSError):
        fresh_pool.get(f"http://localhost:{port}/x", timeout=2)


def test_error_reply_never_desyncs_pooled_keepalive(fresh_pool, tmp_path):
    """Review regression (found by the chaos suite): a volume error
    reply sent BEFORE the request body is drained (failpoint/guard/JWT
    rejections) must close the connection — otherwise the pool recycles
    a socket whose server side still holds the unread body, and the
    NEXT request on it is parsed against those stale bytes (a stock
    HTML 400 poisoning an innocent request)."""
    from seaweedfs_tpu.pb import rpc
    from seaweedfs_tpu.server.master import MasterServer
    from seaweedfs_tpu.server.volume import VolumeServer
    from seaweedfs_tpu.utils import failpoint

    mport = _free_port()
    master = MasterServer(ip="localhost", port=mport,
                          volume_size_limit_mb=64)
    master.start(vacuum_interval=3600)
    vsrv = VolumeServer(directories=[str(tmp_path)],
                        master=f"localhost:{mport}", ip="localhost",
                        port=_free_port(), native=False)
    vsrv.start()
    try:
        from seaweedfs_tpu import operation

        deadline = time.time() + 10
        res = None
        while time.time() < deadline:
            res = operation.submit(f"localhost:{mport}", b"seed-needle",
                                   filename="seed.bin")
            if "fid" in res:
                break
            time.sleep(0.2)
        assert res and "fid" in res, res
        vol_url = f"http://localhost:{vsrv.port}"
        body = os.urandom(64 * 1024)  # large enough to sit unread
        # prime a healthy pooled connection first
        assert fresh_pool.get(f"{vol_url}/{res['fid']}",
                              timeout=10).status == 200
        with failpoint.active("volume.http.write", p=1.0):
            r = fresh_pool.put(f"{vol_url}/{res['fid']}", body=body,
                               timeout=10)
            assert r.status == 500  # the failpoint rejection
        # the fix is the SERVER advertising Connection: close on the
        # error reply, so the pool provably does not retain the
        # desynced connection (without it, whether the next request
        # reads poisoned bytes is a scheduling race — the chaos suite
        # lost it 2 runs out of 3)
        key = ("http", "localhost", vsrv.port)
        assert not fresh_pool._idle.get(key), \
            "desynced connection was returned to the pool"
        # and the next request round-trips cleanly on a fresh dial
        g = fresh_pool.get(f"{vol_url}/{res['fid']}", timeout=10)
        assert g.status == 200 and g.data == b"seed-needle", \
            (g.status, g.data[:80])
    finally:
        vsrv.stop()
        master.stop()
        rpc.reset_channels()


def test_pool_timeout_is_not_replayed(fresh_pool):
    """Review regression: a timeout on a POOLED connection must raise,
    never redial-and-replay — the server may have already received and
    processed the request (a replayed non-idempotent op would apply
    twice and the caller would block for two full timeout windows)."""
    from http.server import BaseHTTPRequestHandler

    from seaweedfs_tpu.utils.httpd import TunedThreadingHTTPServer

    hits = []

    class H(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def do_GET(self):
            hits.append(self.path)
            if self.path == "/slow":
                time.sleep(2.0)  # past the client timeout
            body = b"ok"
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    srv = TunedThreadingHTTPServer(("", 0), H)
    port = srv.server_address[1]
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        # prime the pool with a live connection
        assert fresh_pool.get(f"http://localhost:{port}/fast",
                              timeout=5).status == 200
        with pytest.raises(OSError):
            fresh_pool.get(f"http://localhost:{port}/slow", timeout=0.3)
        time.sleep(2.2)  # let the slow handler finish and log
        assert hits.count("/slow") == 1, "timed-out request was replayed"
    finally:
        srv.shutdown()
        srv.server_close()


# -- read-path identity suite ------------------------------------------------

BIG = os.urandom(64 * 1024)       # > zerocopy_min: native sendfile
SMALL = os.urandom(1024)          # < zerocopy_min: native buffered pread


@pytest.fixture(scope="module")
def native_stack(tmp_path_factory):
    from seaweedfs_tpu.native import native_available
    from seaweedfs_tpu.pb import rpc
    from seaweedfs_tpu.server.master import MasterServer
    from seaweedfs_tpu.server.volume import VolumeServer

    if not native_available():
        pytest.skip("native toolchain unavailable")
    mport = _free_port()
    master = MasterServer(ip="localhost", port=mport,
                          volume_size_limit_mb=64)
    master.start(vacuum_interval=3600)
    vsrv = VolumeServer(
        directories=[str(tmp_path_factory.mktemp("zc"))],
        master=f"localhost:{mport}", ip="localhost", port=_free_port(),
        native=True)
    vsrv.start()
    deadline = time.time() + 10
    while time.time() < deadline and not master.topo.nodes:
        time.sleep(0.05)
    assert vsrv.native_plane is not None, "native plane must be up"
    yield master, vsrv
    vsrv.stop()
    master.stop()
    rpc.reset_channels()


def _put(master, body) -> tuple[str, str]:
    """-> (public url, fid) after uploading `body`."""
    from seaweedfs_tpu.operation import assign

    a = assign(master.address)
    assert not a.error, a.error
    r = requests.put(f"http://{a.url}/{a.fid}", data=body, timeout=30)
    assert r.status_code in (200, 201), r.text
    return a.url, a.fid


def test_sendfile_buffered_python_identity(native_stack):
    """The acceptance hash pin: one object's bytes via native sendfile,
    native buffered, and the python fallback are identical."""
    master, vsrv = native_stack
    want_big, want_small = _sha(BIG), _sha(SMALL)
    url, fid = _put(master, BIG)
    surl, sfid = _put(master, SMALL)
    s = requests.Session()

    sf0 = vsrv.native_plane.sendfile_count()
    r = s.get(f"http://{url}/{fid}", timeout=30)
    assert r.status_code == 200 and _sha(r.content) == want_big
    assert vsrv.native_plane.sendfile_count() == sf0 + 1, \
        "64KB GET must ride the sendfile path"

    r = s.get(f"http://{surl}/{sfid}", timeout=30)
    assert r.status_code == 200 and _sha(r.content) == want_small
    # small bodies take the single-pread buffered path, not sendfile
    assert vsrv.native_plane.sendfile_count() == sf0 + 1

    # python fallback (the admin listener) serves identical bytes
    py = s.get(f"http://localhost:{vsrv.admin_port}/{fid}", timeout=30)
    assert py.status_code == 200 and _sha(py.content) == want_big

    # zero-copy OFF (the A/B arm): same bytes, no sendfile increment
    vsrv.native_plane.set_zerocopy_min(-1)
    try:
        r = s.get(f"http://{url}/{fid}", timeout=30)
        assert _sha(r.content) == want_big
        assert vsrv.native_plane.sendfile_count() == sf0 + 1
    finally:
        vsrv.native_plane.set_zerocopy_min(4096)


def test_range_reassembly_identity(native_stack):
    """Whole == reassembled ranges, on BOTH the native port (sendfile
    206s) and the python fallback port."""
    master, vsrv = native_stack
    url, fid = _put(master, BIG)
    n = len(BIG)
    cuts = [0, n // 3, 2 * n // 3, n]
    for base in (f"http://{url}/{fid}",
                 f"http://localhost:{vsrv.admin_port}/{fid}"):
        parts = []
        for lo, hi in zip(cuts, cuts[1:]):
            r = requests.get(base, timeout=30,
                             headers={"Range": f"bytes={lo}-{hi - 1}"})
            assert r.status_code == 206, (base, r.status_code)
            assert r.headers["Content-Range"] == \
                f"bytes {lo}-{hi - 1}/{n}"
            parts.append(r.content)
        assert _sha(b"".join(parts)) == _sha(BIG)
    # open-ended / over-long / suffix / inverted forms answer
    # identically on both ports (suffix + inverted resolve via the
    # shared utils.http.parse_range — the C++ plane redirects them)
    for base in (f"http://{url}/{fid}",
                 f"http://localhost:{vsrv.admin_port}/{fid}"):
        r = requests.get(base, timeout=30,
                         headers={"Range": f"bytes={n - 100}-"})
        assert r.status_code == 206 and r.content == BIG[-100:]
        r = requests.get(base, timeout=30,
                         headers={"Range": f"bytes=0-{n + 500}"})
        assert r.status_code == 206 and _sha(r.content) == _sha(BIG)
        # suffix: the LAST N bytes (RFC 7233 §2.1)
        r = requests.get(base, timeout=30,
                         headers={"Range": "bytes=-64"})
        assert r.status_code == 206 and r.content == BIG[-64:]
        assert r.headers["Content-Range"] == f"bytes {n - 64}-{n - 1}/{n}"
        # inverted and past-EOF spans: spec-shaped 416
        for bad in ("bytes=500-100", f"bytes={n + 5}-"):
            r = requests.get(base, timeout=30, headers={"Range": bad})
            assert r.status_code == 416, (base, bad, r.status_code)
            assert r.headers["Content-Range"] == f"bytes */{n}"


def test_conditional_get_volume_conformance(native_stack):
    """The conformance matrix on a live volume plane: weak If-None-Match
    lists 304 on BOTH the native and python paths; If-Range validators
    are strong-only; stale validators serve the full 200."""
    master, vsrv = native_stack
    url, fid = _put(master, BIG)
    s = requests.Session()
    g = s.get(f"http://{url}/{fid}", timeout=30)
    etag = g.headers["ETag"]
    lm = g.headers.get("Last-Modified", "")
    assert etag.startswith('"') and etag.endswith('"')
    for base in (f"http://{url}/{fid}",
                 f"http://localhost:{vsrv.admin_port}/{fid}"):
        # weak comparison over a list, native and python alike
        assert s.get(base, timeout=30, headers={
            "If-None-Match": etag}).status_code == 304
        assert s.get(base, timeout=30, headers={
            "If-None-Match": f'W/{etag}'}).status_code == 304
        assert s.get(base, timeout=30, headers={
            "If-None-Match": f'"nope", {etag}'}).status_code == 304
        assert s.get(base, timeout=30, headers={
            "If-None-Match": "*"}).status_code == 304
        assert s.get(base, timeout=30, headers={
            "If-None-Match": '"nope"'}).status_code == 200
        # If-Range: strong etag honors the Range...
        r = s.get(base, timeout=30, headers={
            "Range": "bytes=0-9", "If-Range": etag})
        assert r.status_code == 206 and r.content == BIG[:10]
        # ...a weak tag or a mismatch serves the full 200
        for stale in (f"W/{etag}", '"other"'):
            r = s.get(base, timeout=30, headers={
                "Range": "bytes=0-9", "If-Range": stale})
            assert r.status_code == 200 and _sha(r.content) == _sha(BIG)
        if lm:
            r = s.get(base, timeout=30, headers={
                "Range": "bytes=0-9", "If-Range": lm})
            assert r.status_code == 206 and r.content == BIG[:10]
            later = time.strftime(
                "%a, %d %b %Y %H:%M:%S GMT",
                time.gmtime(time.time() + 3600))
            r = s.get(base, timeout=30, headers={
                "Range": "bytes=0-9", "If-Range": later})
            assert r.status_code == 200 and _sha(r.content) == _sha(BIG)


def test_conditional_get_filer_conformance(native_stack, tmp_path):
    from seaweedfs_tpu.pb import rpc
    from seaweedfs_tpu.server.filer import FilerServer

    master, _ = native_stack
    fsrv = FilerServer(ip="localhost", port=_free_port(),
                       master=master.address, chunk_size=8 * 1024)
    fsrv.start()
    try:
        body = os.urandom(20 * 1024)  # 3 chunks
        base = f"http://{fsrv.address}/cond/obj.bin"
        assert requests.put(base, data=body,
                            timeout=30).status_code < 300
        g = requests.get(base, timeout=30)
        assert g.status_code == 200 and _sha(g.content) == _sha(body)
        etag, lm = g.headers["ETag"], g.headers.get("Last-Modified", "")
        assert requests.get(base, timeout=30, headers={
            "If-None-Match": f'"x", W/{etag}'}).status_code == 304
        assert requests.get(base, timeout=30, headers={
            "If-None-Match": "*"}).status_code == 304
        assert requests.get(base, timeout=30, headers={
            "If-None-Match": '"x"'}).status_code == 200
        r = requests.get(base, timeout=30, headers={
            "Range": "bytes=100-199", "If-Range": etag})
        assert r.status_code == 206 and r.content == body[100:200]
        r = requests.get(base, timeout=30, headers={
            "Range": "bytes=100-199", "If-Range": f'W/{etag}'})
        assert r.status_code == 200 and _sha(r.content) == _sha(body)
        if lm:
            r = requests.get(base, timeout=30, headers={
                "Range": "bytes=0-0", "If-Range": lm})
            assert r.status_code == 206 and r.content == body[:1]
        # filer range-reassembly identity across chunk boundaries
        parts = [requests.get(base, timeout=30, headers={
            "Range": f"bytes={lo}-{lo + 4095}"}).content
            for lo in range(0, len(body), 4096)]
        assert _sha(b"".join(parts)) == _sha(body)
    finally:
        fsrv.stop()
        rpc.reset_channels()


def test_group_commit_window_read_identity(tmp_path, monkeypatch):
    """A needle still inside the group-commit buffer window serves
    hash-identical bytes (the _pread_durable read-retry over the buffer
    window, now reachable over HTTP)."""
    from seaweedfs_tpu.pb import rpc
    from seaweedfs_tpu.server.master import MasterServer
    from seaweedfs_tpu.server.volume import VolumeServer

    monkeypatch.setenv("SWFS_GROUP_COMMIT", "1")
    monkeypatch.setenv("SWFS_GROUP_COMMIT_WINDOW_MS", "700")
    mport = _free_port()
    master = MasterServer(ip="localhost", port=mport,
                          volume_size_limit_mb=64)
    master.start(vacuum_interval=3600)
    vsrv = VolumeServer(directories=[str(tmp_path)],
                        master=f"localhost:{mport}", ip="localhost",
                        port=_free_port(), native=False)
    vsrv.start()
    try:
        from seaweedfs_tpu.operation import assign

        deadline = time.time() + 10
        while time.time() < deadline and not master.topo.nodes:
            time.sleep(0.05)
        a = assign(master.address)
        assert not a.error, a.error
        body = os.urandom(32 * 1024)
        done = []

        def put():
            # acked only after the covering flush: blocks ~window
            r = requests.put(f"http://{a.url}/{a.fid}", data=body,
                             timeout=30)
            done.append(r.status_code)

        t = threading.Thread(target=put, daemon=True)
        t.start()
        got, writer_was_alive = None, False
        poll_deadline = time.time() + 10
        while time.time() < poll_deadline:
            r = requests.get(f"http://{a.url}/{a.fid}", timeout=10)
            if r.status_code == 200:
                got = r.content
                writer_was_alive = t.is_alive()
                break
            time.sleep(0.005)
        t.join(timeout=30)
        assert done == [201], f"PUT failed: {done}"
        assert got is not None, "GET never saw the needle"
        # identity INSIDE the window (the writer was still blocked on
        # its flush when the read completed)
        assert _sha(got) == _sha(body)
        assert writer_was_alive, \
            "read completed only after the flush window - widen WINDOW_MS"
        # and identity after the flush lands
        r = requests.get(f"http://{a.url}/{a.fid}", timeout=10)
        assert _sha(r.content) == _sha(body)
    finally:
        vsrv.stop()
        master.stop()
        rpc.reset_channels()


def test_https_identity_and_handshake_counters(tmp_path, monkeypatch):
    """The encrypted plane serves hash-identical bytes for whole + range
    reads; server/client handshake counters move; the native plane
    stands down under TLS; the wdclient pool dials https and verifies
    the cluster CA."""
    from seaweedfs_tpu.pb import rpc
    from seaweedfs_tpu.security.tls import ensure_self_signed, https_env
    from seaweedfs_tpu.server.master import MasterServer
    from seaweedfs_tpu.server.volume import VolumeServer
    from seaweedfs_tpu.utils.stats import TLS_HANDSHAKES
    from seaweedfs_tpu.wdclient.pool import POOL

    paths = ensure_self_signed(str(tmp_path / "pki"))
    for k, v in https_env(paths).items():
        monkeypatch.setenv(k, v)
    POOL.clear()
    mport = _free_port()
    master = MasterServer(ip="localhost", port=mport,
                          volume_size_limit_mb=64)
    master.start(vacuum_interval=3600)
    vsrv = VolumeServer(directories=[str(tmp_path / "vol")],
                        master=f"localhost:{mport}", ip="localhost",
                        port=_free_port(), native=True)
    vsrv.start()
    try:
        # TLS configured: the C++ plane (plain HTTP only) must stand down
        assert vsrv.native_plane is None
        deadline = time.time() + 10
        while time.time() < deadline and not master.topo.nodes:
            time.sleep(0.05)
        from seaweedfs_tpu.operation import assign

        a = assign(master.address)
        assert not a.error, a.error
        hs_srv0 = TLS_HANDSHAKES.value(role="server")
        hs_cli0 = TLS_HANDSHAKES.value(role="client")
        url = f"https://{a.url}/{a.fid}"
        r = requests.put(url, data=BIG, timeout=30, verify=paths["ca"])
        assert r.status_code in (200, 201), r.text
        g = requests.get(url, timeout=30, verify=paths["ca"])
        assert g.status_code == 200 and _sha(g.content) == _sha(BIG)
        rng = requests.get(url, timeout=30, verify=paths["ca"],
                           headers={"Range": "bytes=100-299"})
        assert rng.status_code == 206 and rng.content == BIG[100:300]
        assert requests.get(url, timeout=30, verify=paths["ca"],
                            headers={"If-None-Match": g.headers["ETag"]}
                            ).status_code == 304
        assert TLS_HANDSHAKES.value(role="server") > hs_srv0
        # the pooled client leg: https + CA verification + handshake
        # accounting, connection reused across requests
        r1 = POOL.get(url, timeout=30)
        r2 = POOL.get(url, timeout=30)
        assert r1.status == 200 and _sha(r1.data) == _sha(BIG)
        assert r2.status == 200 and _sha(r2.data) == _sha(BIG)
        cli_hs = TLS_HANDSHAKES.value(role="client") - hs_cli0
        assert cli_hs == 1, \
            f"pool must amortize the TLS handshake (saw {cli_hs})"
        # a wrong trust root fails FAST (the PR-2 classification):
        # certificate rejection is not retryable
        import ssl

        from seaweedfs_tpu.utils.retry import (
            is_retryable,
            ssl_error_is_retryable,
        )

        other = ensure_self_signed(str(tmp_path / "otherpki"))
        with pytest.raises(requests.exceptions.SSLError) as ei:
            requests.get(url, timeout=10, verify=other["ca"])
        assert not is_retryable(ei.value)
        assert not ssl_error_is_retryable(
            ssl.SSLCertVerificationError("bad cert"))
    finally:
        vsrv.stop()
        master.stop()
        POOL.clear()
        rpc.reset_channels()
