"""E2E tests for the cloud-edge wire clients: GCS / Azure / B2 storage
(remote_storage + replication sinks) and the kafka / SQS / Pub-Sub
notification queues — each against an in-repo fake server that decodes
the wire format independently (tests/fake_cloud.py, tests/fake_kafka.py).

Reference parity targets:
- /root/reference/weed/replication/sink/{gcssink,azuresink,b2sink}/
- /root/reference/weed/remote_storage/{gcs,azure}/
- /root/reference/weed/notification/{kafka,aws_sqs,google_pub_sub}/
"""

import base64

import pytest

from seaweedfs_tpu.cloud import AzureBlobClient, B2Client, GcsClient
from seaweedfs_tpu.notification import (
    QUEUES,
    AwsSqsQueue,
    GooglePubSubQueue,
    KafkaQueue,
    load_configuration,
    set_active,
)
from seaweedfs_tpu.pb import filer_pb2
from seaweedfs_tpu.remote_storage import new_client
from seaweedfs_tpu.replication.sink import new_sink

from .fake_cloud import FakeAzure, FakeB2, FakeGcs, FakePubSub, FakeSqs
from .fake_kafka import FakeKafkaBroker


# ---------------------------------------------------------------------------
# wire clients


@pytest.fixture()
def gcs():
    srv = FakeGcs()
    yield srv
    srv.close()


@pytest.fixture()
def azure():
    srv = FakeAzure()
    yield srv
    srv.close()


@pytest.fixture()
def b2():
    srv = FakeB2()
    yield srv
    srv.close()


def test_gcs_client_crud_and_paging(gcs):
    c = GcsClient("bkt", endpoint=gcs.endpoint, token="tkn")
    for i in range(4):
        c.put_object(f"dir/f{i}", f"payload-{i}".encode() * 10)
    # list pages are 1 item each in the fake — paging must walk all 4
    names = [o.name for o in c.list_objects("dir/")]
    assert names == [f"dir/f{i}" for i in range(4)]
    assert c.get_object("dir/f2") == b"payload-2" * 10
    # ranged read
    assert c.get_object("dir/f2", offset=2, size=5) == b"yload"
    c.delete_object("dir/f1")
    assert [o.name for o in c.list_objects("dir/")] == \
        ["dir/f0", "dir/f2", "dir/f3"]
    with pytest.raises(IOError):
        c.get_object("dir/f1")


def test_azure_client_signed_crud(azure):
    c = AzureBlobClient("ctr", account=azure.account, key=azure.key,
                        endpoint=azure.endpoint)
    for i in range(5):
        c.put_blob(f"a/b{i}", bytes([i]) * (i + 1), "text/plain")
    # the fake recomputed every SharedKey signature: none rejected
    assert azure.rejected == 0
    got = [o.name for o in c.list_blobs("a/")]
    assert got == [f"a/b{i}" for i in range(5)]   # 2-item marker paging
    assert c.get_blob("a/b3") == bytes([3]) * 4
    assert c.get_blob("a/b3", offset=1, size=2) == bytes([3]) * 2
    c.delete_blob("a/b0")
    assert len(list(c.list_blobs("a/"))) == 4


def test_azure_bad_key_rejected(azure):
    import base64 as b64

    bad = b64.b64encode(b"wrong-key").decode()
    c = AzureBlobClient("ctr", account=azure.account, key=bad,
                        endpoint=azure.endpoint)
    with pytest.raises(IOError):
        c.put_blob("x", b"data")
    assert azure.rejected == 1


def test_b2_client_crud_versions_and_reauth():
    # token_uses=4: authorize (1 use implicit in _tokens bookkeeping)
    # then expire mid-sequence to exercise the 401 re-auth path
    srv = FakeB2(token_uses=4)
    try:
        c = B2Client("bkt", key_id=srv.key_id, application_key=srv.app_key,
                     endpoint=srv.endpoint)
        for i in range(5):
            c.upload(f"k/v{i}", f"val-{i}".encode())
        assert srv.auth_calls >= 2   # expired token forced a re-auth
        names = [o.name for o in c.list_files("k/")]
        assert names == [f"k/v{i}" for i in range(5)]  # 2-item pages
        assert c.download("k/v4") == b"val-4"
        assert c.download("k/v4", offset=1, size=3) == b"al-"
        # upload a second version, then delete both through the sink path
        c.upload("k/v0", b"second-version")
        assert c.download("k/v0") == b"second-version"
        c.delete("k/v0")
        assert [o.name for o in c.list_files("k/")] == \
            [f"k/v{i}" for i in range(1, 5)]
    finally:
        srv.close()


def test_b2_bad_credentials():
    srv = FakeB2()
    try:
        c = B2Client("bkt", key_id="nope", application_key="nope",
                     endpoint=srv.endpoint)
        with pytest.raises(IOError):
            c.upload("x", b"d")
    finally:
        srv.close()


# ---------------------------------------------------------------------------
# replication sinks


def _entry(mime="text/plain", directory=False):
    e = filer_pb2.Entry(name="f", is_directory=directory)
    e.attributes.mime = mime
    return e


def test_gcs_sink(gcs):
    sink = new_sink("gcs", bucket="bkt", directory="backup",
                    endpoint=gcs.endpoint)
    sink.create_entry("/buckets/a/x.txt", _entry(), b"hello")
    assert gcs.objects["backup/buckets/a/x.txt"]["data"] == b"hello"
    assert gcs.objects["backup/buckets/a/x.txt"]["ctype"] == "text/plain"
    sink.update_entry("/buckets/a/x.txt", _entry(), b"hello2")
    assert gcs.objects["backup/buckets/a/x.txt"]["data"] == b"hello2"
    sink.create_entry("/buckets/a/dir", _entry(directory=True), None)
    sink.delete_entry("/buckets/a/x.txt", False)
    assert gcs.objects == {}


def test_azure_sink(azure):
    sink = new_sink("azure", container="ctr", account=azure.account,
                    key=azure.key, endpoint=azure.endpoint)
    sink.create_entry("/b/y.bin", _entry("application/octet-stream"),
                      b"\x00\x01")
    assert azure.blobs["b/y.bin"]["data"] == b"\x00\x01"
    sink.delete_entry("/b/y.bin", False)
    assert azure.blobs == {}
    assert azure.rejected == 0


def test_b2_sink(b2):
    sink = new_sink("b2", bucket="bkt", key_id=b2.key_id,
                    application_key=b2.app_key, endpoint=b2.endpoint)
    sink.create_entry("/c/z", _entry(), b"zz")
    assert [f["fileName"] for f in b2.files] == ["c/z"]
    # update writes a second version; delete removes every version
    sink.update_entry("/c/z", _entry(), b"zz2")
    assert len(b2.files) == 2
    sink.delete_entry("/c/z", False)
    assert b2.files == []


# ---------------------------------------------------------------------------
# remote storage clients through the registry


def test_gcs_remote_storage(gcs):
    cl = new_client({"type": "gcs", "bucket": "bkt",
                     "endpoint": gcs.endpoint})
    cl.write_file("/m/a", b"AAA")
    cl.write_file("/m/b", b"BBBB")
    entries = {e.path: e.size for e in cl.traverse("/m/")}
    assert entries == {"/m/a": 3, "/m/b": 4}
    assert cl.read_file("/m/b") == b"BBBB"
    assert cl.read_file("/m/b", offset=1, size=2) == b"BB"
    cl.delete_file("/m/a")
    assert [e.path for e in cl.traverse("/m/")] == ["/m/b"]


def test_azure_remote_storage(azure):
    cl = new_client({"type": "azure", "container": "ctr",
                     "account": azure.account, "key": azure.key,
                     "endpoint": azure.endpoint})
    cl.write_file("/r/q", b"data!")
    assert cl.read_file("/r/q", offset=4, size=1) == b"!"
    assert [e.path for e in cl.traverse("/r/")] == ["/r/q"]
    cl.delete_file("/r/q")
    assert list(cl.traverse("/r/")) == []
    assert azure.rejected == 0


def test_b2_remote_storage(b2):
    cl = new_client({"type": "b2", "bucket": "bkt", "key_id": b2.key_id,
                     "application_key": b2.app_key,
                     "endpoint": b2.endpoint})
    cl.write_file("/p/one", b"1")
    assert cl.read_file("/p/one") == b"1"
    assert [e.path for e in cl.traverse("")] == ["/p/one"]
    cl.delete_file("/p/one")
    assert list(cl.traverse("")) == []


def test_remote_conf_pb_roundtrip():
    from seaweedfs_tpu.pb import remote_pb2
    from seaweedfs_tpu.remote_storage import conf_to_pb, mapping_to_pb

    blob = conf_to_pb("az1", {"type": "azure", "account": "acct",
                              "key": "a2V5", "endpoint": "http://e"})
    rc = remote_pb2.RemoteConf()
    rc.ParseFromString(blob)
    assert (rc.type, rc.azure_account_name, rc.azure_account_key,
            rc.azure_endpoint) == ("azure", "acct", "a2V5", "http://e")
    blob = conf_to_pb("b2x", {"type": "b2", "key_id": "k",
                              "application_key": "ak"})
    rc.ParseFromString(blob)
    assert (rc.backblaze_key_id, rc.backblaze_application_key) == ("k", "ak")
    # bucket-addressed mounts split bucket/path for every cloud kind
    m = remote_pb2.RemoteStorageMapping()
    m.ParseFromString(mapping_to_pb({
        "storages": {"g": {"type": "gcs"}},
        "mounts": {"/mnt/g": {"storage": "g", "remote_path": "bkt/sub"}}}))
    loc = m.mappings["/mnt/g"]
    assert (loc.bucket, loc.path) == ("bkt", "/sub")
    # bucket-only mount: bucket must still split out (wire parity with
    # the reference's whole-bucket remote.mount shape)
    m.ParseFromString(mapping_to_pb({
        "storages": {"g": {"type": "azure"}},
        "mounts": {"/mnt/w": {"storage": "g", "remote_path": "bkt"}}}))
    loc = m.mappings["/mnt/w"]
    assert (loc.bucket, loc.path) == ("bkt", "/")


# ---------------------------------------------------------------------------
# notification queues


def _event(name="ev"):
    ev = filer_pb2.EventNotification()
    ev.new_entry.name = name
    ev.new_entry.attributes.file_size = 7
    return ev


def test_kafka_queue_wire_roundtrip():
    broker = FakeKafkaBroker(topic="weed-events", partitions=3)
    try:
        q = KafkaQueue()
        q.initialize({"hosts": [broker.addr], "topic": "weed-events"})
        for i in range(10):
            q.send_message(f"/dir/file-{i}", _event(f"file-{i}"))
        all_msgs = [m for p in broker.messages.values() for m in p]
        assert len(all_msgs) == 10
        assert broker.crc_failures == 0
        # keyed hash partitioning spread across partitions
        used = [pid for pid, msgs in broker.messages.items() if msgs]
        assert len(used) > 1
        # value decodes as the EventNotification proto
        by_key = {k.decode(): v for k, v in all_msgs}
        ev = filer_pb2.EventNotification()
        ev.ParseFromString(by_key["/dir/file-3"])
        assert ev.new_entry.name == "file-3"
        assert ev.new_entry.attributes.file_size == 7
    finally:
        broker.close()


def test_kafka_same_key_same_partition():
    broker = FakeKafkaBroker(topic="t", partitions=4)
    try:
        q = KafkaQueue()
        q.initialize({"hosts": [broker.addr], "topic": "t"})
        for _ in range(5):
            q.send_message("/same/key", _event())
        used = [pid for pid, msgs in broker.messages.items() if msgs]
        assert len(used) == 1 and len(broker.messages[used[0]]) == 5
    finally:
        broker.close()


def test_kafka_queue_unreachable_fails_fast():
    q = KafkaQueue()
    with pytest.raises(IOError):
        q.initialize({"hosts": ["127.0.0.1:1"], "topic": "t"})


def test_sqs_queue(tmp_path):
    srv = FakeSqs(queue="events")
    try:
        q = AwsSqsQueue()
        q.initialize({"aws_access_key_id": "AK", "aws_secret_access_key":
                      "SK", "region": "us-east-1", "sqs_queue_name":
                      "events", "endpoint": srv.endpoint})
        assert q.queue_url.endswith("/123/events")
        q.send_message("/a/b", _event("b"))
        assert len(srv.messages) == 1
        m = srv.messages[0]
        assert m["MessageAttribute.1.Value.StringValue"] == "/a/b"
        ev = filer_pb2.EventNotification()
        ev.ParseFromString(base64.b64decode(m["MessageBody"]))
        assert ev.new_entry.name == "b"
        assert srv.bad_auth == 0   # every call carried a SigV4 signature
    finally:
        srv.close()


def test_sqs_missing_queue():
    srv = FakeSqs(queue="exists")
    try:
        q = AwsSqsQueue()
        with pytest.raises(RuntimeError):
            q.initialize({"aws_access_key_id": "AK",
                          "aws_secret_access_key": "SK",
                          "sqs_queue_name": "missing",
                          "endpoint": srv.endpoint})
    finally:
        srv.close()


def test_pubsub_queue():
    srv = FakePubSub(project="proj", topic="events")
    try:
        q = GooglePubSubQueue()
        q.initialize({"project_id": "proj", "topic": "events",
                      "endpoint": srv.endpoint, "token": "tok"})
        assert srv.created_topics  # ensure-topic ran
        q.send_message("/x", _event("x"))
        assert len(srv.messages) == 1
        msg = srv.messages[0]
        assert msg["attributes"]["key"] == "/x"
        ev = filer_pb2.EventNotification()
        ev.ParseFromString(base64.b64decode(msg["data"]))
        assert ev.new_entry.name == "x"
    finally:
        srv.close()


def test_load_configuration_kafka():
    broker = FakeKafkaBroker(topic="cfg-topic")
    try:
        q = load_configuration({"notification": {"kafka": {
            "enabled": True, "hosts": [broker.addr],
            "topic": "cfg-topic"}}})
        assert isinstance(q, KafkaQueue)
        q.send_message("/k", _event())
        assert sum(len(m) for m in broker.messages.values()) == 1
    finally:
        set_active(None)
        broker.close()


def test_queue_registry_has_real_cloud_queues():
    assert isinstance(QUEUES["kafka"], KafkaQueue)
    assert isinstance(QUEUES["aws_sqs"], AwsSqsQueue)
    assert isinstance(QUEUES["google_pub_sub"], GooglePubSubQueue)
