"""Protocol-trace goldens for the wire-protocol filer stores.

The store clients and their in-repo fake servers share one author, so a
framing bug could in principle hide by appearing on both sides. These
goldens pin the conversation itself: one canonical session per store —
connect, auth, insert, find, update, list, kv put/get, delete, subtree
delete, close — recorded byte-for-byte through a TCP proxy with all
nondeterminism pinned (os.urandom replaced by a deterministic stream;
entries carry fixed timestamps; request ids are per-connection
counters). `tools/record_goldens.py` writes tests/goldens/<store>.trace
and tests/test_wire_goldens.py re-runs the identical session and
asserts the conversation still matches — any change to either the
client's emitted bytes or the fake's replies fails until the golden is
consciously regenerated (and reviewed as a wire-format change).

Trace format: one line per direction-switch,
``C <hex>`` (client->server) / ``S <hex>`` (server->client), with
``#`` comment lines for annotation.
"""

from __future__ import annotations

import hashlib
import os
import socket
import threading
from contextlib import contextmanager

GOLDEN_DIR = os.path.join(os.path.dirname(__file__), "goldens")


# -- determinism -----------------------------------------------------------

class _DeterministicRandom:
    """sha256-counter byte stream standing in for os.urandom."""

    def __init__(self, seed: bytes = b"seaweedfs-golden"):
        self.seed = seed
        self.n = 0

    def __call__(self, size: int) -> bytes:
        out = b""
        while len(out) < size:
            out += hashlib.sha256(self.seed
                                  + self.n.to_bytes(8, "big")).digest()
            self.n += 1
        return out[:size]


@contextmanager
def pinned_entropy():
    real = os.urandom
    os.urandom = _DeterministicRandom()
    try:
        yield
    finally:
        os.urandom = real


# -- recording proxy -------------------------------------------------------

class RecordingProxy:
    """TCP proxy in front of a fake server, logging both directions as
    a merged (direction, bytes) conversation."""

    def __init__(self, upstream_port: int):
        self.upstream_port = upstream_port
        self.conversation: list[tuple[str, bytes]] = []
        self.pumps: list[threading.Thread] = []
        self._mu = threading.Lock()
        self._listen = socket.socket()
        self._listen.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listen.bind(("localhost", 0))
        self._listen.listen(4)
        self.port = self._listen.getsockname()[1]
        self._stop = threading.Event()
        threading.Thread(target=self._accept, daemon=True).start()

    def _record(self, direction: str, data: bytes) -> None:
        with self._mu:
            if self.conversation and \
                    self.conversation[-1][0] == direction:
                d, prev = self.conversation[-1]
                self.conversation[-1] = (d, prev + data)
            else:
                self.conversation.append((direction, data))

    def _accept(self) -> None:
        while not self._stop.is_set():
            try:
                client, _ = self._listen.accept()
            except OSError:
                return
            upstream = socket.create_connection(
                ("localhost", self.upstream_port))

            def pump(src, dst, direction):
                try:
                    while True:
                        b = src.recv(65536)
                        if not b:
                            break
                        self._record(direction, b)
                        dst.sendall(b)
                except OSError:
                    pass
                finally:
                    try:
                        dst.shutdown(socket.SHUT_WR)
                    except OSError:
                        pass

            for args in ((client, upstream, "C"), (upstream, client, "S")):
                t = threading.Thread(target=pump, args=args, daemon=True)
                t.start()
                self.pumps.append(t)

    def stop(self) -> None:
        self._stop.set()
        try:
            self._listen.close()
        except OSError:
            pass


# -- canonical session -----------------------------------------------------

def golden_cases():
    """(store kind, fake-server factory, store kwargs) for every golden
    — the ONE definition both tools/record_goldens.py and
    tests/test_wire_goldens.py run, so recorder and replayer provably
    exercise the identical session (incl. auth mode/credentials)."""
    from tests.fake_cassandra import FakeCassandraServer
    from tests.fake_mongo import FakeMongoServer
    from tests.fake_mysql import FakeMySqlServer
    from tests.fake_postgres import FakePostgresServer

    return [
        ("postgres",
         lambda: FakePostgresServer(auth="scram", user="weed",
                                    password="golden"),
         dict(user="weed", password="golden")),
        ("mysql",
         lambda: FakeMySqlServer(user="weed", password="golden"),
         dict(user="weed", password="golden")),
        ("mongodb", FakeMongoServer, {}),
        ("cassandra", FakeCassandraServer, {}),
    ]

def canonical_session(store) -> None:
    """The one scripted op sequence every golden records."""
    from seaweedfs_tpu.filer import Attr, Entry

    def entry(path, mtime, content=b""):
        return Entry(full_path=path, content=content,
                     attr=Attr(mtime=mtime, crtime=mtime, mode=0o644,
                               uid=1000, gid=1000))

    store.insert_entry(entry("/g/a.txt", 1_700_000_001, b"golden-a"))
    store.insert_entry(entry("/g/b.txt", 1_700_000_002, b"golden-b"))
    assert store.find_entry("/g/a.txt").content == b"golden-a"
    assert store.find_entry("/g/missing") is None
    store.insert_entry(entry("/g/a.txt", 1_700_000_009, b"golden-a2"))
    names = [e.name for e in
             store.list_directory_entries("/g", limit=16)]
    assert names == ["a.txt", "b.txt"], names
    store.kv_put(b"gkey", bytes(range(32)))
    assert store.kv_get(b"gkey") == bytes(range(32))
    assert store.kv_get(b"absent") is None
    store.delete_entry("/g/b.txt")
    store.delete_folder_children("/g")
    assert store.find_entry("/g/a.txt") is None


def run_session(kind: str, fake_port: int, **store_kwargs
                ) -> list[tuple[str, bytes]]:
    """Run the canonical session for `kind` through a recording proxy
    with pinned entropy -> the merged conversation."""
    from seaweedfs_tpu.filer.filerstore import get_store

    proxy = RecordingProxy(fake_port)
    try:
        with pinned_entropy():
            store = get_store(kind, host="localhost", port=proxy.port,
                              **store_kwargs)
            canonical_session(store)
            store.close()
        # drain: the pump threads exit deterministically on EOF after
        # store.close() (pg Terminate / mysql COM_QUIT are part of the
        # trace) — join them instead of polling a quiet window, which
        # could truncate trailing bytes on a loaded machine
        for t in list(proxy.pumps):
            t.join(timeout=10)
        return list(proxy.conversation)
    finally:
        proxy.stop()


# -- trace file io ---------------------------------------------------------

def save_trace(name: str, conversation: list[tuple[str, bytes]],
               header: str = "") -> str:
    os.makedirs(GOLDEN_DIR, exist_ok=True)
    path = os.path.join(GOLDEN_DIR, f"{name}.trace")
    with open(path, "w") as f:
        f.write(f"# {name} wire-protocol golden (tests/wire_goldens.py)\n")
        if header:
            for line in header.splitlines():
                f.write(f"# {line}\n")
        for d, b in conversation:
            f.write(f"{d} {b.hex()}\n")
    return path


def load_trace(name: str) -> list[tuple[str, bytes]]:
    path = os.path.join(GOLDEN_DIR, f"{name}.trace")
    out: list[tuple[str, bytes]] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            d, hexs = line.split(" ", 1)
            out.append((d, bytes.fromhex(hexs)))
    return out
