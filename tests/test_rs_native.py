"""Native C++ codec must agree byte-for-byte with the numpy reference path.

(The numpy path is itself pinned against the JAX/TPU backend in
test_rs_codec.py, so all three backends form one bit-identity equivalence
class — the property SURVEY.md §7 requires of every ErasureCoder plugin.)
"""

import numpy as np
import pytest

from seaweedfs_tpu.ops import rs_native
from seaweedfs_tpu.ops.rs_cpu import RSCodecCPU

pytestmark = pytest.mark.skipif(
    not rs_native.available(), reason="native toolchain unavailable"
)


@pytest.mark.parametrize("k,m", [(10, 4), (6, 3), (12, 4), (3, 2)])
def test_encode_matches_numpy(k, m):
    rng = np.random.default_rng(42)
    data = rng.integers(0, 256, size=(k, 4096 + 13), dtype=np.uint8)
    cpu = RSCodecCPU(k, m)
    nat = rs_native.RSCodecNative(k, m)
    np.testing.assert_array_equal(cpu.encode_parity(data), nat.encode_parity(data))


def test_reconstruct_matches_numpy():
    rng = np.random.default_rng(7)
    k, m = 10, 4
    cpu = RSCodecCPU(k, m)
    nat = rs_native.RSCodecNative(k, m)
    shards = cpu.encode(
        np.concatenate(
            [rng.integers(0, 256, size=(k, 999), dtype=np.uint8),
             np.zeros((m, 999), np.uint8)]
        )
    )
    lost = [0, 5, 11, 13]
    present = {i: shards[i] for i in range(k + m) if i not in lost}
    got = nat.reconstruct(dict(present))
    for i in lost:
        np.testing.assert_array_equal(got[i], shards[i])
    got_d = nat.reconstruct_data(dict(present))
    assert sorted(got_d) == [0, 5]
    assert nat.verify(shards)


def test_crc32c_matches_python():
    import zlib

    from seaweedfs_tpu.storage import crc as crc_mod

    rng = np.random.default_rng(3)
    for n in (0, 1, 7, 8, 9, 4096, 100003):
        buf = rng.integers(0, 256, size=n, dtype=np.uint8).tobytes()
        assert rs_native.crc32c_native(buf) == crc_mod.crc32c(buf)


def test_native_is_faster_than_numpy():
    import time

    rng = np.random.default_rng(0)
    data = rng.integers(0, 256, size=(10, 1 << 20), dtype=np.uint8)
    cpu, nat = RSCodecCPU(10, 4), rs_native.RSCodecNative(10, 4)
    cpu.encode_parity(data); nat.encode_parity(data)  # warm

    def t(f):
        t0 = time.perf_counter()
        f(data)
        return time.perf_counter() - t0

    assert t(nat.encode_parity) < t(cpu.encode_parity)
