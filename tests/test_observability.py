"""Observability + config + security wiring (SURVEY.md §5.1/§5.5/§5.6):
TOML config tiers, grace profiling, metrics exposition/push, JWT writes,
guard whitelist."""

import json
import os
import socket
import time

import pytest
import requests

from seaweedfs_tpu.pb import rpc
from seaweedfs_tpu.security import Guard, gen_write_jwt
from seaweedfs_tpu.server.master import MasterServer
from seaweedfs_tpu.server.volume import VolumeServer
from seaweedfs_tpu.utils import config as cfg
from seaweedfs_tpu.utils.stats import gather


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


# -- config ---------------------------------------------------------------

def test_config_search_and_env_expansion(tmp_path, monkeypatch):
    monkeypatch.setenv("SECRET_VAL", "s3cr3t")
    (tmp_path / "custom.toml").write_text(
        'title = "${SECRET_VAL}"\n[nested]\nvalue = 42\n')
    monkeypatch.setattr(cfg, "SEARCH_PATHS", [str(tmp_path)])
    conf = cfg.load_config("custom")
    assert conf["title"] == "s3cr3t"
    assert cfg.get_path(conf, "nested.value") == 42
    assert cfg.get_path(conf, "nested.missing", "dflt") == "dflt"
    assert cfg.load_config("absent") == {}
    with pytest.raises(FileNotFoundError):
        cfg.load_config("absent", required=True)


def test_security_config_loading(tmp_path, monkeypatch):
    import base64

    key = base64.b64encode(b"topsecret").decode()
    (tmp_path / "security.toml").write_text(
        f'[jwt.signing]\nkey = "{key}"\nexpires_after_seconds = 30\n'
        f'[guard]\nwhite_list = ["127.0.0.1"]\n')
    monkeypatch.setattr(cfg, "SEARCH_PATHS", [str(tmp_path)])
    sec = cfg.load_security_config()
    assert sec["write_key"] == b"topsecret"
    assert sec["expires_sec"] == 30
    assert sec["whitelist"] == ["127.0.0.1"]


# -- grace ----------------------------------------------------------------

def test_grace_profiling_dumps(tmp_path):
    import subprocess
    import sys

    cpu = tmp_path / "cpu.pprof"
    mem = tmp_path / "mem.txt"
    code = (
        "from seaweedfs_tpu.utils.grace import setup_profiling\n"
        f"setup_profiling({str(cpu)!r}, {str(mem)!r})\n"
        "x = sum(i * i for i in range(10000))\n"
    )
    subprocess.run([sys.executable, "-c", code], check=True,
                   cwd="/root/repo")
    assert cpu.exists() and cpu.stat().st_size > 0
    assert mem.exists()
    import pstats

    stats = pstats.Stats(str(cpu))
    assert stats.total_calls > 0


# -- metrics --------------------------------------------------------------

def test_metrics_exposition_format():
    text = gather()
    assert "# TYPE SeaweedFS_volumeServer_request_seconds histogram" in text
    assert "SeaweedFS_filerStore_ops" in text


def test_exposition_escapes_hostile_label_values():
    """ISSUE 7 satellite regression: a collection (or any label value)
    containing `\"`, `\\` or a newline must be escaped per the text
    exposition format — unescaped, every sample after it fails to
    parse and the whole scrape is lost."""
    import re

    from seaweedfs_tpu.utils import stats

    c = stats.Counter("SeaweedFS_test_hostile_ops", "test only")
    try:
        hostile = 'evil"col\\with\nnewline'
        c.inc(collection=hostile, op="put")
        out = c.render()
        lines = out.splitlines()
        # the render stays line-oriented: exactly HELP + TYPE + 1 sample
        assert len(lines) == 3, lines
        sample = lines[2]
        assert '\\"' in sample and "\\\\" in sample and "\\n" in sample
        # the escaped line round-trips through the exposition grammar
        m = re.fullmatch(
            r'SeaweedFS_test_hostile_ops\{(?P<labels>(?:[a-zA-Z_]\w*='
            r'"(?:[^"\\\n]|\\.)*",?)+)\} (?P<v>[0-9.e+-]+)', sample)
        assert m, sample
        # and unescaping recovers the original value
        esc = re.search(r'collection="((?:[^"\\\n]|\\.)*)"', sample)
        unescaped = (esc.group(1).replace("\\n", "\n")
                     .replace('\\"', '"').replace("\\\\", "\\"))
        assert unescaped == hostile
    finally:
        with stats._REG_MU:
            stats._REGISTRY.remove(c)


def test_every_metric_family_is_in_readme_table():
    """ISSUE 7 satellite: the README metrics table is the contract —
    every SeaweedFS_* family registered in utils/stats.py must appear
    in it (a new family without docs fails CI)."""
    import re

    from seaweedfs_tpu.utils import stats

    readme = open(os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "README.md")).read()
    documented = set(re.findall(r"`(SeaweedFS_\w+)`", readme))
    with stats._REG_MU:
        registered = {m.name for m in stats._REGISTRY
                      if m.name.startswith("SeaweedFS_")}
    missing = registered - documented
    assert not missing, \
        f"metric families missing from README's metrics table: {missing}"


def test_metrics_push_and_master_broadcast(tmp_path):
    # a fake push gateway capturing PUTs
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
    import threading

    received = []

    class GW(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_PUT(self):
            n = int(self.headers.get("Content-Length") or 0)
            received.append((self.path, self.rfile.read(n)))
            self.send_response(200)
            self.send_header("Content-Length", "0")
            self.end_headers()

    gw_port = _free_port()
    gw = ThreadingHTTPServer(("", gw_port), GW)
    threading.Thread(target=gw.serve_forever, daemon=True).start()

    mport = _free_port()
    master = MasterServer(ip="localhost", port=mport,
                          volume_size_limit_mb=64,
                          metrics_address=f"http://localhost:{gw_port}",
                          metrics_interval_sec=1)
    master.start(vacuum_interval=3600)
    vsrv = VolumeServer(directories=[str(tmp_path / "v")],
                        master=f"localhost:{mport}", ip="localhost",
                        port=_free_port(), pulse_seconds=1)
    vsrv.start()
    try:
        deadline = time.time() + 15
        while time.time() < deadline and not received:
            time.sleep(0.2)
        assert received, "volume server never pushed metrics"
        path, body = received[0]
        assert path.startswith("/metrics/job/volumeServer-")
        assert b"SeaweedFS_" in body
    finally:
        vsrv.stop()
        master.stop()
        gw.shutdown()
        rpc.reset_channels()


def test_metrics_push_survives_flapping_sink(tmp_path):
    """ISSUE 7 satellite chaos: the push loop must survive a sink that
    is down when pushing starts, recover when it comes up, keep going
    when it flaps to 503s, and count every outcome — a refused
    connection must never kill the thread."""
    import threading
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    from seaweedfs_tpu.utils import stats

    received = []
    fail_mode = {"on": False}

    class GW(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_PUT(self):
            n = int(self.headers.get("Content-Length") or 0)
            body = self.rfile.read(n)
            if fail_mode["on"]:
                self.send_response(503)
                self.send_header("Content-Length", "0")
                self.end_headers()
                return
            received.append(body)
            self.send_response(200)
            self.send_header("Content-Length", "0")
            self.end_headers()

    gw_port = _free_port()
    err0 = stats.METRICS_PUSH_OPS.value(outcome="error")
    ok0 = stats.METRICS_PUSH_OPS.value(outcome="ok")
    # the sink does NOT exist yet: first pushes hit connection refused
    stop = stats.start_push(f"http://localhost:{gw_port}", "flaptest",
                            interval_sec=1)
    try:
        deadline = time.time() + 10
        while time.time() < deadline and \
                stats.METRICS_PUSH_OPS.value(outcome="error") <= err0:
            time.sleep(0.1)
        assert stats.METRICS_PUSH_OPS.value(outcome="error") > err0, \
            "refused connections were never counted"
        # sink comes up: the SAME loop must recover and deliver
        gw = ThreadingHTTPServer(("", gw_port), GW)
        threading.Thread(target=gw.serve_forever, daemon=True).start()
        try:
            deadline = time.time() + 15
            while time.time() < deadline and not received:
                time.sleep(0.1)
            assert received, "push loop never recovered after the sink " \
                             "came up"
            assert b"SeaweedFS_" in received[0]
            assert stats.METRICS_PUSH_OPS.value(outcome="ok") > ok0
            # flap to 503s: deliveries fail (counted), loop survives
            fail_mode["on"] = True
            errs = stats.METRICS_PUSH_OPS.value(outcome="error")
            deadline = time.time() + 10
            while time.time() < deadline and \
                    stats.METRICS_PUSH_OPS.value(outcome="error") <= errs:
                time.sleep(0.1)
            assert stats.METRICS_PUSH_OPS.value(outcome="error") > errs
            # and heals again
            fail_mode["on"] = False
            n = len(received)
            deadline = time.time() + 15
            while time.time() < deadline and len(received) <= n:
                time.sleep(0.1)
            assert len(received) > n, "loop did not heal after the flap"
        finally:
            gw.shutdown()
    finally:
        stop()


_CAMEL_KEY = __import__("re").compile(r"^[a-z][a-zA-Z0-9]*$")


def _assert_camel_keys(obj, path=""):
    if isinstance(obj, dict):
        for k, v in obj.items():
            # schema keys must be camelCase; DATA keys (chip labels
            # like "-"/"0", addresses with ":") are not identifiers
            # and are exempt
            if __import__("re").match(r"^[A-Za-z]", k):
                assert _CAMEL_KEY.match(k), \
                    f"non-camelCase key {k!r} at {path or '<root>'}"
            _assert_camel_keys(v, f"{path}.{k}")
    elif isinstance(obj, list):
        for i, v in enumerate(obj):
            _assert_camel_keys(v, f"{path}[{i}]")


def test_status_schema_unified_across_servers(tmp_path):
    """ISSUE 7 satellite: every server's /status reports version/
    startedAt/uptimeSeconds at top level, and the per-plane sections
    (EcDispatch, Scrub, EcStream, GroupCommit, ChunkCache, Trace, and
    the ISSUE-8 Qos section) use consistent camelCase keys all the way
    down."""
    from seaweedfs_tpu.s3api.server import S3Server
    from seaweedfs_tpu.server.filer import FilerServer

    mport = _free_port()
    master = MasterServer(ip="localhost", port=mport,
                          volume_size_limit_mb=64)
    master.start(vacuum_interval=3600)
    vsrv = VolumeServer(directories=[str(tmp_path / "v")],
                        master=f"localhost:{mport}", ip="localhost",
                        port=_free_port(), pulse_seconds=1)
    vsrv.start()
    fsrv = FilerServer(ip="localhost", port=_free_port(),
                       master=f"localhost:{mport}")
    fsrv.start()
    s3 = S3Server(port=_free_port(), filer=fsrv.address)
    s3.start()
    try:
        addrs = [master.address, vsrv.address, fsrv.address,
                 f"localhost:{s3.port}"]
        for addr in addrs:
            st = requests.get(f"http://{addr}/status", timeout=10).json()
            assert st["version"].startswith("seaweedfs-tpu"), (addr, st)
            assert isinstance(st["startedAt"], int)
            assert st["uptimeSeconds"] >= 0
            assert "Trace" in st
            # QoS plane (ISSUE 8): every server exposes its admission /
            # grant / pressure view even while the plane is observe-only
            assert "Qos" in st, addr
        vol = requests.get(f"http://{vsrv.address}/status",
                           timeout=10).json()
        for section in ("GroupCommit", "EcDispatch", "EcStream",
                        "Scrub", "Trace", "Qos"):
            assert section in vol, section
            _assert_camel_keys(vol[section], section)
        assert 0.0 <= vol["Qos"]["pressure"] <= 1.0
        assert vol["Qos"]["governor"]["enabled"] is False  # env unset
        fil = requests.get(f"http://{fsrv.address}/status",
                           timeout=10).json()
        for section in ("ChunkCache", "FidLease", "Trace", "Qos"):
            assert section in fil, section
            _assert_camel_keys(fil[section], section)
        assert fil["Qos"]["tenantAdmission"]["plane"] == "filer"
        mst = requests.get(f"http://{master.address}/status",
                           timeout=10).json()
        assert "ledger" in mst["Qos"]
        _assert_camel_keys(mst["Qos"], "Qos")
    finally:
        s3.stop()
        fsrv.stop()
        vsrv.stop()
        master.stop()
        rpc.reset_channels()


# -- JWT + guard ----------------------------------------------------------

def test_jwt_protected_writes(tmp_path):
    key = b"jwt-test-key"
    mport = _free_port()
    master = MasterServer(ip="localhost", port=mport,
                          volume_size_limit_mb=64, write_jwt_key=key)
    master.start(vacuum_interval=3600)
    vsrv = VolumeServer(directories=[str(tmp_path / "v")],
                        master=f"localhost:{mport}", ip="localhost",
                        port=_free_port(), pulse_seconds=1,
                        write_jwt_key=key)
    vsrv.start()
    deadline = time.time() + 10
    while time.time() < deadline and not master.topo.nodes:
        time.sleep(0.05)
    try:
        r = requests.get(f"http://localhost:{mport}/dir/assign?count=1",
                         timeout=10).json()
        assert r.get("auth"), "master did not mint a JWT"
        fid, url = r["fid"], r["url"]
        # unauthorized write is refused
        bad = requests.put(f"http://{url}/{fid}", data=b"x", timeout=10)
        assert bad.status_code == 401
        # with the minted token it lands
        ok = requests.put(f"http://{url}/{fid}", data=b"authorized",
                          headers={"Authorization": f"Bearer {r['auth']}"},
                          timeout=10)
        assert ok.status_code == 201, ok.text
        # reads are open (no read key configured)
        got = requests.get(f"http://{url}/{fid}", timeout=10)
        assert got.content == b"authorized"
        # a token for a different fid is refused
        other = gen_write_jwt(key, "99,deadbeef01")
        bad2 = requests.put(f"http://{url}/{fid}", data=b"y", timeout=10,
                            headers={"Authorization": f"Bearer {other}"})
        assert bad2.status_code == 401
    finally:
        vsrv.stop()
        master.stop()
        rpc.reset_channels()


def test_guard_whitelist(tmp_path):
    mport = _free_port()
    master = MasterServer(ip="localhost", port=mport, volume_size_limit_mb=64)
    master.start(vacuum_interval=3600)
    vsrv = VolumeServer(directories=[str(tmp_path / "v")],
                        master=f"localhost:{mport}", ip="localhost",
                        port=_free_port(), pulse_seconds=1,
                        guard=Guard(whitelist=["10.9.9.9"]))
    vsrv.start()
    deadline = time.time() + 10
    while time.time() < deadline and not master.topo.nodes:
        time.sleep(0.05)
    try:
        r = requests.get(f"http://{vsrv.address}/status", timeout=10)
        assert r.status_code == 403  # we come from 127.0.0.1
    finally:
        vsrv.stop()
        master.stop()
        rpc.reset_channels()


# -- status UIs (master_ui/volume_server_ui/filer_ui templates.go) ---------

def test_status_ui_pages(tmp_path):
    from seaweedfs_tpu.server.filer import FilerServer

    mport = _free_port()
    master = MasterServer(ip="localhost", port=mport, volume_size_limit_mb=64)
    master.start(vacuum_interval=3600)
    vsrv = VolumeServer(directories=[str(tmp_path / "vol")],
                        master=f"localhost:{mport}", ip="localhost",
                        port=_free_port(), pulse_seconds=1)
    vsrv.start()
    fsrv = FilerServer(ip="localhost", port=_free_port(),
                       master=f"localhost:{mport}")
    fsrv.start()
    try:
        deadline = time.time() + 10
        while time.time() < deadline and not master.topo.nodes:
            time.sleep(0.05)

        r = requests.get(f"http://localhost:{mport}/", timeout=10)
        assert r.status_code == 200
        assert "text/html" in r.headers["Content-Type"]
        assert "Master" in r.text and vsrv.address in r.text
        assert "Topology" in r.text

        r = requests.get(f"http://{vsrv.address}/ui", timeout=10)
        assert r.status_code == 200 and "Volume Server" in r.text
        assert "Disks" in r.text

        # filer: browsers (Accept: text/html) get the directory browser,
        # API clients keep getting JSON
        requests.post(f"http://{fsrv.address}/ui-docs/readme.txt",
                      files={"file": ("readme.txt", b"hello ui")}, timeout=10)
        r = requests.get(f"http://{fsrv.address}/ui-docs/",
                         headers={"Accept": "text/html"}, timeout=10)
        assert r.status_code == 200 and "readme.txt" in r.text
        assert "<table>" in r.text
        r = requests.get(f"http://{fsrv.address}/ui-docs/", timeout=10)
        assert r.headers["Content-Type"].startswith("application/json")
        assert "readme.txt" in json.dumps(r.json())
    finally:
        fsrv.stop()
        vsrv.stop()
        master.stop()
        rpc.reset_channels()
