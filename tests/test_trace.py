"""End-to-end request tracing (ISSUE 7): span propagation across
S3 → filer → volume HTTP/gRPC → EC dispatch, W3C traceparent parsing
(hostile headers re-root, never 500), tail-based retention, the
`trace.dump` shell command, and the dispatch-attribution attributes
(queue wait, batch factor, chip) on a degraded read under 4-shard loss.
"""

from __future__ import annotations

import io
import socket
import time

import numpy as np
import pytest
import requests

from seaweedfs_tpu.operation import submit
from seaweedfs_tpu.pb import rpc
from seaweedfs_tpu.pb import volume_server_pb2 as vs
from seaweedfs_tpu.s3api.server import S3Server
from seaweedfs_tpu.server.filer import FilerServer
from seaweedfs_tpu.server.master import MasterServer
from seaweedfs_tpu.server.volume import VolumeServer
from seaweedfs_tpu.shell.commands.trace_cmd import gather_trace
from seaweedfs_tpu.shell.env import CommandEnv
from seaweedfs_tpu.shell.registry import run_command
from seaweedfs_tpu.storage.ec_locate import Geometry
from seaweedfs_tpu.storage.file_id import parse_file_id
from seaweedfs_tpu.utils import failpoint, trace

TEST_GEO = Geometry(large_block=10000, small_block=100)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


# -- traceparent parsing ----------------------------------------------------

def test_parse_traceparent_valid():
    tid = "a" * 32
    sid = "b" * 16
    assert trace.parse_traceparent(f"00-{tid}-{sid}-01") == (tid, sid,
                                                             True)
    assert trace.parse_traceparent(f"00-{tid}-{sid}-00") == (tid, sid,
                                                             False)
    # future version with extra fields still parses the leading four
    assert trace.parse_traceparent(f"cc-{tid}-{sid}-01-extra") == (
        tid, sid, True)


@pytest.mark.parametrize("bad", [
    None, "", "garbage", "00", "00-short-b-01",
    "00-" + "z" * 32 + "-" + "b" * 16 + "-01",     # non-hex trace id
    "00-" + "0" * 32 + "-" + "b" * 16 + "-01",     # all-zero trace id
    "00-" + "a" * 32 + "-" + "0" * 16 + "-01",     # all-zero span id
    "ff-" + "a" * 32 + "-" + "b" * 16 + "-01",     # forbidden version
    "00-" + "a" * 31 + "-" + "b" * 16 + "-01",     # wrong length
    "00-" + "a" * 32 + "-" + "b" * 16 + "-zz",     # bad flags
    12345, b"00-aa-bb-01",
])
def test_parse_traceparent_malformed(bad):
    assert trace.parse_traceparent(bad) is None


# -- span mechanics ---------------------------------------------------------

def test_span_nesting_and_store():
    with trace.span("root") as root:
        assert trace.current() is root
        with trace.span("child") as child:
            assert child.trace_id == root.trace_id
            assert child.parent_id == root.span_id
        assert trace.current() is root
    assert trace.current() is None
    spans = trace.STORE.trace(root.trace_id)
    assert {s["name"] for s in spans} == {"root", "child"}


def test_child_only_without_parent_records_nothing():
    before = trace.STORE.recorded
    with trace.span("lonely", child_only=True) as sp:
        sp.set_attr(x=1)  # absorbing no-op
    assert trace.STORE.recorded == before
    assert sp.traceparent() == ""


def test_disabled_plane_is_noop(monkeypatch):
    monkeypatch.setenv("SWFS_TRACE", "0")
    trace.refresh_config()  # the env knob is TTL-cached on the hot path
    try:
        before = trace.STORE.recorded
        with trace.span("off") as sp:
            assert sp is trace.NOOP
            assert trace.traceparent() == ""
        assert trace.STORE.recorded == before
    finally:
        monkeypatch.undo()
        trace.refresh_config()


def test_retention_pins_error_and_slow(monkeypatch):
    monkeypatch.setenv("SWFS_TRACE_SLOW_MS", "10")
    trace.refresh_config()
    try:
        with trace.span("fast-ok"):
            pass
        with trace.span("slow-one") as slow:
            time.sleep(0.02)
        with pytest.raises(RuntimeError):
            with trace.span("err-one") as err:
                raise RuntimeError("boom")
        retained = {s["traceId"]
                    for s in trace.STORE.retained_summaries()}
        assert slow.trace_id in retained
        assert err.trace_id in retained
        err_spans = trace.STORE.trace(err.trace_id)
        assert any("boom" in s["error"] for s in err_spans)
    finally:
        monkeypatch.undo()
        trace.refresh_config()


def test_carrier_roundtrip_headers_and_grpc_metadata():
    with trace.span("origin") as sp:
        headers = trace.inject_headers({"X-Other": "1"})
        assert trace.parse_traceparent(headers["traceparent"])[0] == \
            sp.trace_id
    # HTTP-headers style carrier
    with trace.span("server-side", carrier=headers) as child:
        assert child.trace_id == sp.trace_id
    # gRPC invocation-metadata style carrier (list of pairs)
    md = [("user-agent", "x"), ("traceparent", sp.traceparent())]
    assert trace.carrier_has_context(md)
    with trace.span("grpc-side", carrier=md) as child2:
        assert child2.trace_id == sp.trace_id
    assert not trace.carrier_has_context([("user-agent", "x")])


def test_malformed_carrier_reroots():
    with trace.span("rerooted",
                    carrier={"traceparent": "not-a-traceparent"}) as sp:
        assert len(sp.trace_id) == 32  # fresh root, not a crash


def test_histogram_exemplars_link_to_traces():
    from seaweedfs_tpu.utils import stats

    h = stats.Histogram("SeaweedFS_test_exemplar_seconds", "test only")
    try:
        with trace.span("exemplar-src") as sp:
            h.observe(0.05, type="t")
        ex = h.exemplars(type="t")
        assert any(v["traceId"] == sp.trace_id for v in ex.values())
        with_ex = h.render(exemplars=True)
        assert f'trace_id="{sp.trace_id}"' in with_ex
        assert " # {" not in h.render()  # plain 0.0.4 stays clean
    finally:
        with stats._REG_MU:
            stats._REGISTRY.remove(h)


# -- live cluster: propagation, degraded read, trace.dump, fuzz ------------

@pytest.fixture(scope="module")
def trace_stack(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("trace")
    mport = _free_port()
    master = MasterServer(ip="localhost", port=mport,
                          volume_size_limit_mb=64)
    master.start(vacuum_interval=3600)
    volumes = []
    for i in range(2):
        v = VolumeServer(directories=[str(tmp / f"vol{i}")],
                         master=f"localhost:{mport}", ip="localhost",
                         port=_free_port(), pulse_seconds=1,
                         ec_geometry=TEST_GEO)
        v.start()
        volumes.append(v)
    fsrv = FilerServer(ip="localhost", port=_free_port(),
                       master=f"localhost:{mport}",
                       store_dir=str(tmp / "filer"),
                       chunk_size=32 * 1024)
    fsrv.start()
    s3 = S3Server(port=_free_port(), filer=fsrv.address)
    s3.start()
    deadline = time.time() + 10
    while time.time() < deadline and len(master.topo.nodes) < 2:
        time.sleep(0.05)
    assert len(master.topo.nodes) == 2
    yield master, volumes, fsrv, s3
    s3.stop()
    fsrv.stop()
    for v in volumes:
        v.stop()
    master.stop()
    rpc.reset_channels()


def _wait(cond, timeout=15.0, msg="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return
        time.sleep(0.1)
    raise AssertionError(f"timed out waiting for {msg}")


def test_degraded_s3_read_produces_cross_server_trace(trace_stack):
    """The acceptance path: one S3 GET of an EC'd object under 4-shard
    loss returns an X-Trace-Id whose trace — gathered by `trace.dump`
    from every server — covers s3 ingress → filer ladder → volume →
    remote shard gRPC → dispatch-batched reconstruct, with queue-wait,
    batch-factor and chip attributes present, and spans from the filer
    plus BOTH volume servers."""
    master, volumes, fsrv, s3 = trace_stack

    # --- stage an EC'd object whose shards split across both servers
    rng = np.random.default_rng(7)
    body = rng.integers(0, 256, size=3000, dtype=np.uint8).tobytes()
    requests.put(f"http://localhost:{s3.port}/tracebkt", timeout=10)
    r = requests.put(f"http://localhost:{s3.port}/tracebkt/obj.bin",
                     data=body, timeout=30)
    assert r.status_code == 200, r.text
    # the chunk fid names the volume to convert
    entry = fsrv.filer.find_entry("/buckets/tracebkt/obj.bin")
    vid = parse_file_id(entry.chunks[0].file_id).volume_id
    src = next(v for v in volumes if v.store.has_volume(vid))
    dst = next(v for v in volumes if v is not src)
    stub_src = rpc.volume_stub(rpc.grpc_address(src.address))
    stub_dst = rpc.volume_stub(rpc.grpc_address(dst.address))
    stub_src.VolumeMarkReadonly(
        vs.VolumeMarkReadonlyRequest(volume_id=vid), timeout=30)
    stub_src.VolumeEcShardsGenerate(
        vs.VolumeEcShardsGenerateRequest(volume_id=vid), timeout=120)
    # move shards 7..13 to the second server so any reconstruct must
    # gather survivors over gRPC
    moved = list(range(7, 14))
    stub_dst.VolumeEcShardsCopy(
        vs.VolumeEcShardsCopyRequest(
            volume_id=vid, shard_ids=moved, copy_ecx_file=True,
            copy_vif_file=True, source_data_node=src.address),
        timeout=120)
    stub_src.VolumeUnmount(vs.VolumeUnmountRequest(volume_id=vid),
                           timeout=30)
    stub_src.VolumeEcShardsDelete(
        vs.VolumeEcShardsDeleteRequest(volume_id=vid, shard_ids=moved),
        timeout=30)
    stub_src.VolumeEcShardsMount(
        vs.VolumeEcShardsMountRequest(volume_id=vid,
                                      shard_ids=list(range(7))),
        timeout=30)
    stub_dst.VolumeEcShardsMount(
        vs.VolumeEcShardsMountRequest(volume_id=vid, shard_ids=moved),
        timeout=30)
    _wait(lambda: len(master.topo.lookup_ec_shards(vid) or {}) == 14,
          msg="all 14 shards registered")

    # the filer chunk cache was write-through-populated at PUT; the
    # degraded read must hit the volume plane, where the loss lives
    saved_cache = fsrv.chunk_cache
    fsrv.chunk_cache = None
    lost = "|".join(f"shard={i}," for i in range(4))
    try:
        with failpoint.active("ec.shard.read", p=1.0, match=lost) as fp:
            got = requests.get(
                f"http://localhost:{s3.port}/tracebkt/obj.bin",
                timeout=60)
            assert got.status_code == 200
            assert got.content == body
            assert fp.hits > 0, "shard loss never injected"
        trace_id = got.headers.get("X-Trace-Id", "")
        assert len(trace_id) == 32, got.headers
    finally:
        fsrv.chunk_cache = saved_cache

    # --- trace.dump gathers the trace from every server it touched
    env = CommandEnv(master.address, filer=fsrv.address)
    spans, targets = gather_trace(env, trace_id,
                                  extra=[f"localhost:{s3.port}"])
    assert len(targets) >= 4  # master + 2 volume servers + filer + s3
    names = {s["name"] for s in spans}
    assert "s3.request" in names
    assert "filer.read" in names
    assert "filer.chunk_read" in names
    assert "volume.read" in names or "grpc.VolumeEcShardRead" in names
    assert "volume.ec.reconstruct" in names
    # acceptance: spans from >= 3 servers incl. the filer and BOTH
    # volume servers (the reconstruct gathered survivors over gRPC)
    servers = {s["server"] for s in spans if s["server"]}
    assert fsrv.address in servers
    assert {src.address, dst.address} <= servers, servers
    assert len(servers) >= 3
    # dispatch attribution on the reconstruct span(s)
    recon = [s for s in spans if s["name"] == "volume.ec.reconstruct"
             and "dispatchBatchSlabs" in s["attrs"]]
    assert recon, "no reconstruct span carried dispatch attribution"
    a = recon[0]["attrs"]
    assert a["dispatchBatchSlabs"] >= 1
    assert a["dispatchQueueWaitMs"] >= 0
    assert "dispatchChip" in a
    assert a["survivors"] >= 10
    # every span of the tree shares the one trace id
    assert {s["traceId"] for s in spans} == {trace_id}

    # --- the shell command renders it
    out = io.StringIO()
    assert run_command(env, f"trace.dump -trace={trace_id} "
                            f"-server=localhost:{s3.port}", out=out) == 0
    text = out.getvalue()
    assert trace_id in text
    assert "s3.request" in text and "volume.ec.reconstruct" in text

    # cache hit/miss attribution: with the cache back on, a re-read
    # marks its chunk-read span as a hit
    got2 = requests.get(f"http://localhost:{s3.port}/tracebkt/obj.bin",
                        timeout=30)
    tid2 = got2.headers["X-Trace-Id"]
    spans2 = trace.STORE.trace(tid2)
    reads = [s for s in spans2 if s["name"] == "filer.chunk_read"]
    assert reads and all(s["attrs"].get("cache") in ("hit", "miss")
                         for s in reads)


def test_malformed_traceparent_never_500s_always_reroots(trace_stack):
    """Fuzz the ingress planes with hostile traceparent headers: no
    request may fail because of one, and each response must carry a
    FRESH trace id (re-rooted, not parroting garbage)."""
    master, volumes, fsrv, s3 = trace_stack
    hostile = [
        "garbage", "00", "00-xx-yy-zz", "\x00\x01binary",
        "00-" + "0" * 32 + "-" + "0" * 16 + "-01",
        "00-" + "f" * 400 + "-" + "b" * 16 + "-01",
        "ff-" + "a" * 32 + "-" + "b" * 16 + "-01",
        "00-" + "a" * 32 + "-" + "b" * 16 + "-zz",
        "00-a-b-c-d-e-f-g", ",,,///---",
    ]
    targets = [
        f"http://localhost:{s3.port}/tracebkt/obj.bin",
        f"http://{fsrv.address}/buckets/tracebkt/obj.bin",
        f"http://{master.address}/dir/assign",
    ]
    for url in targets:
        for tp in hostile:
            try:
                r = requests.get(url, headers={"traceparent": tp},
                                 timeout=30)
            except requests.RequestException as e:
                raise AssertionError(f"{url} with {tp!r} broke the "
                                     f"connection: {e}")
            assert r.status_code < 500, (url, tp, r.status_code, r.text)
            tid = r.headers.get("X-Trace-Id", "")
            assert len(tid) == 32 and tid not in tp, (url, tp, tid)
    # a VALID traceparent, by contrast, is honored end to end
    good_tid = "c" * 32
    r = requests.get(targets[0],
                     headers={"traceparent":
                              f"00-{good_tid}-{'d' * 16}-01"},
                     timeout=30)
    assert r.status_code == 200
    assert r.headers["X-Trace-Id"] == good_tid
    assert trace.STORE.trace(good_tid), "propagated trace left no spans"


def test_grpc_metadata_propagation(trace_stack):
    """A gRPC call made inside a span carries the context as metadata;
    the servicer's handler span lands in the same trace with the
    server's address on it."""
    master, volumes, fsrv, s3 = trace_stack
    v = volumes[0]
    with trace.span("test.client") as sp:
        stub = rpc.volume_stub(rpc.grpc_address(v.address))
        stub.Ping(vs.PingRequest(), timeout=10)
    spans = trace.STORE.trace(sp.trace_id)
    grpc_spans = [s for s in spans if s["name"] == "grpc.Ping"]
    assert grpc_spans and grpc_spans[0]["server"] == v.address
    # background chatter without a span context creates NO grpc spans
    before = trace.STORE.recorded
    stub.Ping(vs.PingRequest(), timeout=10)
    with trace.STORE._lock:
        stray = [s for s in trace.STORE._ring
                 if s.name == "grpc.Ping" and s.trace_id != sp.trace_id]
    assert not stray
    assert trace.STORE.recorded == before


def test_retained_trace_span_cap(monkeypatch):
    """A client reusing ONE traceparent forever must not grow a pinned
    trace without bound (the 'all bounds are hard' contract)."""
    monkeypatch.setenv("SWFS_TRACE_SLOW_MS", "1")
    trace.refresh_config()
    try:
        tid = "e" * 32
        parent = (tid, "f" * 16, True)
        with trace.span("pin-me", parent=parent):
            time.sleep(0.005)  # slow -> pinned
        for _ in range(trace.RETAINED_TRACE_SPAN_CAP + 50):
            with trace.span("repeat", parent=parent):
                pass
        with trace.STORE._lock:
            held = len(trace.STORE._retained.get(tid, ()))
        assert held <= trace.RETAINED_TRACE_SPAN_CAP
    finally:
        monkeypatch.undo()
        trace.refresh_config()


def test_no_stale_trace_id_on_keepalive_connection(trace_stack):
    """A traced request followed by an untraced admin request on the
    SAME keep-alive connection must not leak the previous X-Trace-Id."""
    master, volumes, fsrv, s3 = trace_stack
    s = requests.Session()
    s.trust_env = False
    r1 = s.get(f"http://localhost:{s3.port}/tracebkt/obj.bin",
               timeout=30)
    assert r1.headers.get("X-Trace-Id")
    r2 = s.get(f"http://localhost:{s3.port}/status", timeout=30)
    assert "X-Trace-Id" not in r2.headers, r2.headers
    r3 = s.get(f"http://{fsrv.address}/status", timeout=30)
    assert "X-Trace-Id" not in r3.headers


def test_debug_traces_endpoints_and_status_trace_section(trace_stack):
    master, volumes, fsrv, s3 = trace_stack
    with trace.span("endpoint-probe") as sp:
        pass
    for addr in (master.address, volumes[0].address, fsrv.address,
                 f"localhost:{s3.port}"):
        r = requests.get(f"http://{addr}/debug/traces", timeout=10)
        assert r.status_code == 200
        payload = r.json()
        assert "retained" in payload and "store" in payload
        r = requests.get(f"http://{addr}/debug/traces",
                         params={"trace": sp.trace_id}, timeout=10)
        assert r.json()["traceId"] == sp.trace_id
        st = requests.get(f"http://{addr}/status", timeout=10).json()
        assert st["Trace"]["enabled"] is True


def test_submit_roundtrip_under_trace_has_assign_and_upload(trace_stack):
    """The client verbs attribute their own latency: a submit() inside
    a span yields client.assign + client.upload + master.grpc children."""
    master, volumes, fsrv, s3 = trace_stack
    with trace.span("client-verbs") as sp:
        res = submit(master.address, b"traced-bytes", filename="t.bin")
        assert "fid" in res, res
    spans = trace.STORE.trace(sp.trace_id)
    names = {s["name"] for s in spans}
    assert "client.assign" in names
    assert "client.upload" in names
    assert "volume.write" in names  # the upload's server-side half
    # group-commit attribution rides the write span as attributes
    w = next(s for s in spans if s["name"] == "volume.write")
    assert w["attrs"].get("gcRole") in ("leader", "follower")
    assert w["attrs"].get("gcWaitMs", -1) >= 0
