"""Pipeline-scale EC tests — BASELINE.md configs #2 (large volume), #4
(concurrent volumes) and the production-geometry coverage the reference's
own tests lack (ec_test.go:16-19 shrinks block sizes; here we encode at the
real 1GB/1MB geometry and at a large-row/small-row boundary).

Covers the round-1 verdict's weak spots: the encoder is now an N-deep
three-stage pipeline (reader thread -> device queue -> writer), so these
tests assert (a) depth does not change bytes, (b) concurrent encodes do not
serialize behind a global lock, (c) a >=1GB volume encodes through the real
shell `ec.encode` path against a live volume server, (d) boundary math holds
at production block sizes.
"""

import hashlib
import io
import os
import shutil
import socket
import threading
import time

import numpy as np
import pytest

from seaweedfs_tpu.ops.rs_native import RSCodecNative, available as native_available
from seaweedfs_tpu.storage import ec_files
from seaweedfs_tpu.storage.ec_locate import Geometry, locate_data

pytestmark = pytest.mark.skipif(
    not native_available(), reason="native codec toolchain unavailable"
)


def _shard_hashes(base: str, geo: Geometry) -> list[str]:
    out = []
    for i in range(geo.total_shards):
        h = hashlib.sha256()
        with open(geo.shard_file_name(base, i), "rb") as f:
            while chunk := f.read(1 << 20):
                h.update(chunk)
        out.append(h.hexdigest())
    return out


def _write_dat(path: str, size: int, seed: int = 0) -> None:
    """Fast ~non-uniform .dat: one random MB tiled with a per-slab stamp."""
    rng = np.random.default_rng(seed)
    blob = rng.integers(0, 256, 1 << 20, dtype=np.uint8).tobytes()
    with open(path, "wb") as f:
        written = 0
        i = 0
        while written < size:
            chunk = i.to_bytes(8, "big") + blob[8:]
            take = min(len(chunk), size - written)
            f.write(chunk[:take])
            written += take
            i += 1


# ---------------------------------------------------------------------------
# (a) pipeline depth never changes output bytes


def test_pipeline_depth_identity(tmp_path):
    geo = Geometry(large_block=1 << 20, small_block=1 << 16)
    base1, base2 = str(tmp_path / "d1"), str(tmp_path / "d4")
    _write_dat(base1 + ".dat", 23 * (1 << 20) + 12345)
    shutil.copy(base1 + ".dat", base2 + ".dat")
    coder = RSCodecNative(10, 4)

    s1 = ec_files.generate_ec_files(base1, coder, geo, batch_size=1 << 18,
                                    pipeline_depth=1)
    s4 = ec_files.generate_ec_files(base2, coder, geo, batch_size=1 << 18,
                                    pipeline_depth=4)
    assert _shard_hashes(base1, geo) == _shard_hashes(base2, geo)
    for s in (s1, s4):
        assert s.batches > 0 and s.bytes > 0
        assert s.read_s > 0 and s.dispatch_s > 0 and s.write_s > 0
        assert s.wall_s > 0 and s.overlap_ratio > 0


# ---------------------------------------------------------------------------
# (b) concurrent encodes share the device queue instead of serializing.
# One-core CI can't show CPU-parallel speedup, so the "device" is simulated:
# encode_parity returns a future whose result is ready `delay` after launch
# (sleeps release the GIL, exactly like an async TPU dispatch).


class _DelayedParity:
    def __init__(self, shape, ready_at):
        self._shape = shape
        self._ready_at = ready_at

    def __array__(self, dtype=None, copy=None):
        now = time.perf_counter()
        if now < self._ready_at:
            time.sleep(self._ready_at - now)
        return np.zeros(self._shape, dtype=np.uint8)


class _DelayCoder:
    """Models an async accelerator with `delay` seconds per slab."""

    def __init__(self, data_shards=10, parity_shards=4, delay=0.02):
        self.data_shards, self.parity_shards = data_shards, parity_shards
        self.total_shards = data_shards + parity_shards
        self.delay = delay

    def encode_parity(self, data):
        return _DelayedParity((self.parity_shards, data.shape[1]),
                              time.perf_counter() + self.delay)


def _encode_n(tmp_path, tag, n, coder, geo, threads):
    bases = []
    for v in range(n):
        base = str(tmp_path / f"{tag}{v}")
        _write_dat(base + ".dat", 16 * (1 << 18), seed=v)  # 16 slabs each
        bases.append(base)
    spans = {}

    def run(b):
        t = time.perf_counter()
        ec_files.generate_ec_files(b, coder, geo, batch_size=1 << 18)
        spans[b] = (t, time.perf_counter())

    t0 = time.perf_counter()
    if threads:
        ts = [threading.Thread(target=run, args=(b,)) for b in bases]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
    else:
        for b in bases:
            run(b)
    return time.perf_counter() - t0, list(spans.values())


def test_concurrent_encodes_do_not_serialize(tmp_path):
    geo = Geometry(large_block=1 << 20, small_block=1 << 18)
    # 250ms device latency per slab, paid ~once per volume by the pipeline:
    # concurrency across volumes must hide it across volumes too. Timing on
    # a loaded 1-core CI box jitters, so allow a retry before failing.
    coder = _DelayCoder(delay=0.25)
    last = None
    for attempt in range(3):
        sub = tmp_path / f"try{attempt}"
        sub.mkdir()
        serial, _ = _encode_n(sub, "s", 4, coder, geo, threads=False)
        concurrent, spans = _encode_n(sub, "c", 4, coder, geo, threads=True)
        # all four encodes must be in flight simultaneously at some point
        latest_start = max(s for s, _ in spans)
        earliest_end = min(e for _, e in spans)
        assert latest_start < earliest_end, spans
        if concurrent < 0.8 * serial:
            return
        last = (serial, concurrent)
    raise AssertionError(f"concurrent encodes serialized: {last}")


# ---------------------------------------------------------------------------
# (c) >=1GB volume through the real shell ec.encode against a live server
# (BASELINE config #2 at production 1GB/1MB geometry), then every needle
# byte-verified through the shard layout and a sample re-read over HTTP
# through the EC serving path.


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_gigabyte_shell_encode(tmp_path):
    import requests

    from seaweedfs_tpu.pb import rpc
    from seaweedfs_tpu.server.master import MasterServer
    from seaweedfs_tpu.server.volume import VolumeServer
    from seaweedfs_tpu.shell.registry import run_command
    from seaweedfs_tpu.shell.env import CommandEnv
    from seaweedfs_tpu.storage.needle import Needle
    from seaweedfs_tpu.storage.volume import Volume

    geo = Geometry()  # production 1GB / 1MB blocks
    vol_dir = tmp_path / "vol"
    vol_dir.mkdir()

    # Build a ~1.02GB volume offline through the real needle codec.
    rng = np.random.default_rng(7)
    blob = rng.integers(0, 256, 1 << 20, dtype=np.uint8).tobytes()
    v = Volume(str(vol_dir), "", 1)
    extents = {}  # fid key -> (offset, record bytes asserted later via .dat copy)
    total = 0
    nid = 0
    while total < (1 << 30) + (1 << 22):
        nid += 1
        size = (1 << 20) - 128 * (nid % 17)
        payload = nid.to_bytes(8, "big") + blob[8:size]
        n = Needle.create(nid, 0x2026, payload)
        off, sz, _ = v.write_needle(n, check_cookie=False)
        extents[nid] = (off, payload)
        total += size
    v.close()
    dat_size = os.path.getsize(vol_dir / "1.dat")
    assert dat_size >= 1 << 30
    shutil.copy(vol_dir / "1.dat", tmp_path / "orig.dat")

    mport = _free_port()
    master = MasterServer(ip="localhost", port=mport, volume_size_limit_mb=2048)
    master.start(vacuum_interval=3600)
    vsrv = VolumeServer(directories=[str(vol_dir)], master=f"localhost:{mport}",
                        ip="localhost", port=_free_port(), pulse_seconds=1,
                        coder=RSCodecNative(10, 4), ec_geometry=geo)
    vsrv.start()
    try:
        deadline = time.time() + 10
        while time.time() < deadline and len(master.topo.nodes) < 1:
            time.sleep(0.05)
        assert master.topo.nodes, "volume server did not register"

        env = CommandEnv(master.address)
        out = io.StringIO()
        assert run_command(env, "lock", out) == 0
        t0 = time.perf_counter()
        code = run_command(env, "ec.encode -volumeId 1", out)
        encode_s = time.perf_counter() - t0
        assert code == 0, out.getvalue()
        print(f"\n[ec-scale] 1GB shell ec.encode: {dat_size / 1e9:.2f} GB in "
              f"{encode_s:.1f}s = {dat_size / 1e9 / encode_s:.2f} GB/s host "
              f"pipeline (native CPU coder, 1-core CI)")

        # every needle extent byte-identical through the shard layout
        base = str(vol_dir / "1")
        with open(tmp_path / "orig.dat", "rb") as orig:
            for nid, (off, payload) in extents.items():
                ln = min(4096, len(payload))
                orig.seek(off)
                want = orig.read(ln)
                got = bytearray()
                for iv in locate_data(geo, dat_size, off, ln):
                    sid, soff = iv.to_shard_id_and_offset(geo)
                    with open(geo.shard_file_name(base, sid), "rb") as f:
                        f.seek(soff)
                        got += f.read(iv.size)
                assert bytes(got) == want, f"needle {nid} mismatch via shards"

        # a sample of needles re-read over HTTP through the EC serving path
        url = f"http://{vsrv.address}"
        for nid in list(extents)[:: max(1, len(extents) // 25)]:
            r = requests.get(f"{url}/1,{nid:x}00002026", timeout=30)
            assert r.status_code == 200, (nid, r.status_code)
            assert r.content == extents[nid][1], f"needle {nid} HTTP mismatch"
    finally:
        vsrv.stop()
        master.stop()
        rpc.reset_channels()


# ---------------------------------------------------------------------------
# (d) large-row/small-row boundary at production-scale small blocks


@pytest.mark.slow
def test_large_row_boundary_production_blocks(tmp_path):
    geo = Geometry(large_block=32 << 20, small_block=1 << 20)
    base = str(tmp_path / "b")
    size = 10 * (32 << 20) + 37 * (1 << 20) + 4321  # 1 large row + small tail
    _write_dat(base + ".dat", size, seed=3)
    n_large, n_small = geo.row_counts(size)
    assert n_large >= 1 and n_small >= 1

    coder = RSCodecNative(10, 4)
    ec_files.generate_ec_files(base, coder, geo)
    before = _shard_hashes(base, geo)

    # oracle: random intervals through the shard layout == .dat bytes
    rng = np.random.default_rng(11)
    with open(base + ".dat", "rb") as f:
        for _ in range(200):
            off = int(rng.integers(0, size - 1))
            ln = int(rng.integers(1, min(3 << 20, size - off)))
            f.seek(off)
            want = f.read(ln)
            got = bytearray()
            for iv in locate_data(geo, size, off, ln):
                sid, soff = iv.to_shard_id_and_offset(geo)
                with open(geo.shard_file_name(base, sid), "rb") as sf:
                    sf.seek(soff)
                    got += sf.read(iv.size)
            assert bytes(got) == want, (off, ln)

    # kill 3 shards (incl. one data shard) and rebuild bit-identically
    for sid in (2, 11, 13):
        os.remove(geo.shard_file_name(base, sid))
    rebuilt = ec_files.rebuild_ec_files(base, coder, geo)
    assert sorted(rebuilt) == [2, 11, 13]
    assert _shard_hashes(base, geo) == before
