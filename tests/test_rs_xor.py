"""Bit-identity of the packed-word XOR kernels (rs_xor) vs the gf256 oracle.

Covers both the XLA-fused and the Pallas (interpreter) variants, encode and
decode matrices, several geometries, and non-aligned byte counts.
"""

import numpy as np
import pytest

from seaweedfs_tpu.ops import gf256
from seaweedfs_tpu.ops.rs_xor import (
    apply_matrix_xor,
    apply_matrix_xor_pallas,
    xor_coefficients,
)


def _oracle(matrix: np.ndarray, data: np.ndarray) -> np.ndarray:
    out = np.zeros((matrix.shape[0], data.shape[1]), dtype=np.uint8)
    for r in range(matrix.shape[0]):
        acc = np.zeros(data.shape[1], dtype=np.uint8)
        for c in range(matrix.shape[1]):
            acc ^= gf256.gf_mul_vec(
                np.full_like(data[c], matrix[r, c]), data[c]
            )
        out[r] = acc
    return out


@pytest.mark.parametrize("k,m", [(10, 4), (6, 3), (12, 4), (3, 2)])
def test_xla_matches_oracle(k, m):
    rng = np.random.default_rng(k * 100 + m)
    matrix = gf256.parity_matrix(k, m)
    data = rng.integers(0, 256, size=(k, 4096), dtype=np.uint8)
    got = np.asarray(apply_matrix_xor(matrix, data))
    np.testing.assert_array_equal(got, _oracle(matrix, data))


@pytest.mark.parametrize("b", [1, 3, 4, 513, 4096])
def test_xla_odd_lengths(b):
    rng = np.random.default_rng(b)
    matrix = gf256.parity_matrix(10, 4)
    data = rng.integers(0, 256, size=(10, b), dtype=np.uint8)
    got = np.asarray(apply_matrix_xor(matrix, data))
    np.testing.assert_array_equal(got, _oracle(matrix, data))


def test_decode_matrix_identity():
    rng = np.random.default_rng(9)
    k, m = 10, 4
    matrix = gf256.parity_matrix(k, m)
    data = rng.integers(0, 256, size=(k, 2048), dtype=np.uint8)
    parity = _oracle(matrix, data)
    shards = np.concatenate([data, parity], axis=0)
    present = [i for i in range(k + m) if i not in (0, 5, 11, 13)]
    dec, used = gf256.decode_matrix_for(k, m, present)
    stacked = shards[list(used)]
    got = np.asarray(apply_matrix_xor(dec, stacked))
    np.testing.assert_array_equal(got, data)


def test_pallas_interpret_matches_oracle():
    rng = np.random.default_rng(3)
    matrix = gf256.parity_matrix(10, 4)
    from seaweedfs_tpu.ops.rs_xor import TILE_BYTES

    for b in (TILE_BYTES, 2 * TILE_BYTES + 100):
        data = rng.integers(0, 256, size=(10, b), dtype=np.uint8)
        got = np.asarray(apply_matrix_xor_pallas(matrix, data, interpret=True))
        np.testing.assert_array_equal(got, _oracle(matrix, data))


def test_coefficients_shape_and_values():
    matrix = gf256.parity_matrix(6, 3)
    k = xor_coefficients(matrix)
    assert k.shape == (3, 6, 8)
    # j=0 multiplier is the matrix entry itself
    np.testing.assert_array_equal(k[:, :, 0], matrix.astype(np.int32))
    # doubling law: k[..., j+1] = gfmul(k[..., j], 2)
    for j in range(7):
        np.testing.assert_array_equal(
            k[:, :, j + 1].astype(np.uint8),
            gf256.gf_mul_vec(k[:, :, j].astype(np.uint8), np.uint8(2)),
        )


@pytest.mark.parametrize("kind", ["xor-xla", "mxu-xla"])
def test_codec_dispatch_env_override(kind, monkeypatch):
    """RSCodecJax honors SEAWEEDFS_TPU_KERNEL and stays bit-identical."""
    from seaweedfs_tpu.ops.rs_jax import RSCodecJax

    monkeypatch.setenv("SEAWEEDFS_TPU_KERNEL", kind)
    rng = np.random.default_rng(11)
    coder = RSCodecJax(10, 4)
    data = rng.integers(0, 256, size=(10, 20000), dtype=np.uint8)
    shards = np.asarray(coder.encode(data))
    matrix = gf256.parity_matrix(10, 4)
    np.testing.assert_array_equal(shards[10:], _oracle(matrix, data))
    present = {i: shards[i] for i in range(14) if i not in (1, 4, 10, 12)}
    rebuilt = coder.reconstruct(present)
    for i in (1, 4, 10, 12):
        np.testing.assert_array_equal(np.asarray(rebuilt[i]), shards[i])


def test_bad_kernel_env_rejected(monkeypatch):
    from seaweedfs_tpu.ops.rs_jax import RSCodecJax

    monkeypatch.setenv("SEAWEEDFS_TPU_KERNEL", "xor_pallas")
    coder = RSCodecJax(10, 4)
    data = np.zeros((10, 64), dtype=np.uint8)
    with pytest.raises(ValueError, match="SEAWEEDFS_TPU_KERNEL"):
        coder.encode_parity(data)


@pytest.mark.parametrize("k,m", [(10, 4), (6, 3), (3, 2)])
def test_sel_xla_matches_oracle(k, m):
    from seaweedfs_tpu.ops.rs_xor import apply_matrix_sel

    rng = np.random.default_rng(k * 7 + m)
    matrix = gf256.parity_matrix(k, m)
    data = rng.integers(0, 256, size=(k, 4096), dtype=np.uint8)
    got = np.asarray(apply_matrix_sel(matrix, data))
    np.testing.assert_array_equal(got, _oracle(matrix, data))


def test_sel_pallas_interpret_matches_oracle():
    from seaweedfs_tpu.ops.rs_xor import TILE_BYTES, apply_matrix_sel_pallas

    rng = np.random.default_rng(5)
    matrix = gf256.parity_matrix(10, 4)
    for b in (TILE_BYTES, TILE_BYTES + 333):
        data = rng.integers(0, 256, size=(10, b), dtype=np.uint8)
        got = np.asarray(apply_matrix_sel_pallas(matrix, data,
                                                 interpret=True))
        np.testing.assert_array_equal(got, _oracle(matrix, data))


def test_sel_decode_roundtrip(monkeypatch):
    from seaweedfs_tpu.ops.rs_jax import RSCodecJax

    monkeypatch.setenv("SEAWEEDFS_TPU_KERNEL", "sel-xla")
    rng = np.random.default_rng(31)
    coder = RSCodecJax(10, 4)
    data = rng.integers(0, 256, size=(10, 30000), dtype=np.uint8)
    shards = np.asarray(coder.encode(data))
    present = {i: shards[i] for i in range(14) if i not in (0, 6, 9, 13)}
    rebuilt = coder.reconstruct(present)
    for i in (0, 6, 9, 13):
        np.testing.assert_array_equal(np.asarray(rebuilt[i]), shards[i])


def test_sel_decode_routes_to_runtime_operand(monkeypatch):
    """With sel-* selected, decode matrices must run through the xor
    (runtime-operand) path — no per-survivor-set sel specialization."""
    from seaweedfs_tpu.ops import rs_xor
    from seaweedfs_tpu.ops.rs_jax import RSCodecJax

    monkeypatch.setenv("SEAWEEDFS_TPU_KERNEL", "sel-xla")
    coder = RSCodecJax(10, 4)
    rng = np.random.default_rng(12)
    data = rng.integers(0, 256, size=(10, 8192), dtype=np.uint8)
    shards = np.asarray(coder.encode(data))
    before = {k for k in rs_xor._sel_runners}
    present = {i: shards[i] for i in range(14) if i not in (1, 2, 3, 11)}
    rebuilt = coder.reconstruct(present)
    for i in (1, 2, 3, 11):
        np.testing.assert_array_equal(np.asarray(rebuilt[i]), shards[i])
    dec_keys = [k for k in rs_xor._sel_runners
                if k not in before and k[0][0] == "dec"]
    assert not dec_keys, dec_keys
