"""In-process pure-python MongoDB OP_MSG server: enough of the command
set (find/getMore with filters+sort+limit, update with upsert, delete,
createIndexes, saslStart/saslContinue SCRAM-SHA-256) to exercise the
real mongodb filer store (seaweedfs_tpu/filer/stores/mongo_wire.py)
end to end. BSON framing is decoded with the store's own codec but the
SCRAM proof is verified with independent RFC 7677 math, and cursors are
deliberately returned in small batches so getMore really runs."""

from __future__ import annotations

import base64
import hashlib
import hmac
import os
import re
import socket
import struct
import threading

from seaweedfs_tpu.filer.stores.bson import Regex, decode_doc, encode_doc

OP_MSG = 2013
BATCH = 3          # small on purpose: forces the client's getMore loop


class FakeMongoServer:
    def __init__(self, *, user: str = "", password: str = ""):
        self.user = user
        self.password = password
        self.docs: list[dict] = []      # {directory, name, meta}
        self._dblock = threading.Lock()
        self._cursors: dict[int, list[dict]] = {}
        self._next_cursor = 1000
        self._listen = socket.socket()
        self._listen.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listen.bind(("localhost", 0))
        self._listen.listen(8)
        self.port = self._listen.getsockname()[1]
        self._stop = threading.Event()
        threading.Thread(target=self._accept_loop, daemon=True).start()

    def stop(self) -> None:
        self._stop.set()
        try:
            self._listen.close()
        except OSError:
            pass

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._listen.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    @staticmethod
    def _recv_exact(conn: socket.socket, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = conn.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("client gone")
            buf += chunk
        return buf

    def _serve(self, conn: socket.socket) -> None:
        authed = not self.password
        scram: dict | None = None
        try:
            while not self._stop.is_set():
                header = self._recv_exact(conn, 16)
                length, rid, _rto, opcode = struct.unpack("<iiii", header)
                payload = self._recv_exact(conn, length - 16)
                if opcode != OP_MSG or payload[4] != 0:
                    self._reply(conn, rid, {"ok": 0, "code": 2,
                                            "errmsg": "bad message"})
                    continue
                cmd, _ = decode_doc(payload, 5)
                verb = next(iter(cmd))
                if verb == "saslStart":
                    reply, scram = self._sasl_start(cmd)
                elif verb == "saslContinue":
                    reply, scram = self._sasl_continue(cmd, scram)
                    if reply.get("done") and reply.get("ok") == 1:
                        authed = True
                elif not authed:
                    reply = {"ok": 0, "code": 13,
                             "errmsg": "command requires authentication"}
                else:
                    reply = self._dispatch(verb, cmd)
                self._reply(conn, rid, reply)
        except (ConnectionError, OSError, struct.error, ValueError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    @staticmethod
    def _reply(conn: socket.socket, rid: int, doc: dict) -> None:
        body = b"\x00\x00\x00\x00\x00" + encode_doc(doc)
        conn.sendall(struct.pack("<iiii", 16 + len(body), 1, rid, OP_MSG)
                     + body)

    # -- SCRAM-SHA-256 server side (independent implementation) -----------

    def _sasl_start(self, cmd: dict):
        if cmd.get("mechanism") != "SCRAM-SHA-256":
            return {"ok": 0, "code": 278, "errmsg": "bad mechanism"}, None
        client_first = cmd["payload"].decode()
        bare = client_first.split(",", 2)[2]
        attrs = dict(kv.split("=", 1) for kv in bare.split(","))
        if self.user and attrs.get("n") != self.user:
            return {"ok": 0, "code": 11,
                    "errmsg": "authentication failed"}, None
        snonce = attrs["r"] + base64.b64encode(os.urandom(12)).decode()
        salt, iters = os.urandom(16), 4096
        server_first = (f"r={snonce},s={base64.b64encode(salt).decode()},"
                        f"i={iters}")
        state = {"bare": bare, "server_first": server_first,
                 "snonce": snonce, "salt": salt, "iters": iters}
        return {"ok": 1, "conversationId": 1, "done": False,
                "payload": server_first.encode()}, state

    def _sasl_continue(self, cmd: dict, state: dict | None):
        if not state:
            return {"ok": 0, "code": 17,
                    "errmsg": "no SASL session"}, None
        final = cmd["payload"].decode()
        fattrs = dict(kv.split("=", 1) for kv in final.split(","))
        final_bare = final[:final.rindex(",p=")]
        if fattrs.get("r") != state["snonce"]:
            return {"ok": 0, "code": 11, "errmsg": "nonce mismatch"}, None
        salted = hashlib.pbkdf2_hmac("sha256", self.password.encode(),
                                     state["salt"], state["iters"])
        ckey = hmac.new(salted, b"Client Key", hashlib.sha256).digest()
        stored = hashlib.sha256(ckey).digest()
        auth_msg = ",".join([state["bare"], state["server_first"],
                             final_bare]).encode()
        csig = hmac.new(stored, auth_msg, hashlib.sha256).digest()
        proof = base64.b64decode(fattrs["p"])
        if hashlib.sha256(bytes(a ^ b for a, b in
                                zip(proof, csig))).digest() != stored:
            return {"ok": 0, "code": 11,
                    "errmsg": "authentication failed"}, None
        skey = hmac.new(salted, b"Server Key", hashlib.sha256).digest()
        ssig = hmac.new(skey, auth_msg, hashlib.sha256).digest()
        return {"ok": 1, "conversationId": 1, "done": True,
                "payload": b"v=" + base64.b64encode(ssig)}, None

    # -- commands ----------------------------------------------------------

    def _dispatch(self, verb: str, cmd: dict) -> dict:
        if verb == "createIndexes":
            return {"ok": 1}
        if verb == "update":
            return self._update(cmd)
        if verb == "find":
            return self._find(cmd)
        if verb == "getMore":
            return self._get_more(cmd)
        if verb == "delete":
            return self._delete(cmd)
        if verb in ("ping", "hello", "isMaster", "endSessions"):
            return {"ok": 1}
        return {"ok": 0, "code": 59, "errmsg": f"no such command {verb!r}"}

    @staticmethod
    def _match_value(cond, value) -> bool:
        if isinstance(cond, Regex):
            return bool(re.search(cond.pattern, value or ""))
        if isinstance(cond, dict):
            for op, rhs in cond.items():
                if op == "$gt":
                    if not (value or "") > rhs:
                        return False
                elif op == "$gte":
                    if not (value or "") >= rhs:
                        return False
                elif op == "$lt":
                    if not (value or "") < rhs:
                        return False
                elif op == "$regex":
                    pat = rhs.pattern if isinstance(rhs, Regex) else rhs
                    if not re.search(pat, value or ""):
                        return False
                else:
                    raise ValueError(f"unsupported operator {op}")
            return True
        return value == cond

    def _match(self, doc: dict, flt: dict) -> bool:
        for k, cond in flt.items():
            if k == "$or":
                if not any(self._match(doc, sub) for sub in cond):
                    return False
            elif not self._match_value(cond, doc.get(k)):
                return False
        return True

    def _update(self, cmd: dict) -> dict:
        n = 0
        with self._dblock:
            for u in cmd.get("updates", []):
                q, upd = u["q"], u["u"]
                sets = upd.get("$set", {})
                hit = False
                for doc in self.docs:
                    if self._match(doc, q):
                        doc.update(sets)
                        hit = True
                        n += 1
                if not hit and u.get("upsert"):
                    doc = dict(q)
                    doc.update(sets)
                    self.docs.append(doc)
                    n += 1
        return {"ok": 1, "n": n}

    def _find(self, cmd: dict) -> dict:
        flt = cmd.get("filter", {})
        with self._dblock:
            rows = [dict(d) for d in self.docs if self._match(d, flt)]
        for key, direction in reversed(list(cmd.get("sort", {}).items())):
            rows.sort(key=lambda d: d.get(key) or "",
                      reverse=direction < 0)
        limit = cmd.get("limit", 0)
        if limit:
            rows = rows[:limit]
        first, rest = rows[:BATCH], rows[BATCH:]
        cid = 0
        if rest:
            with self._dblock:
                cid = self._next_cursor
                self._next_cursor += 1
                self._cursors[cid] = rest
        return {"ok": 1, "cursor": {"firstBatch": first, "id": cid,
                                    "ns": "seaweedfs.filemeta"}}

    def _get_more(self, cmd: dict) -> dict:
        cid = cmd["getMore"]
        with self._dblock:
            rest = self._cursors.get(cid, [])
            batch, rest = rest[:BATCH], rest[BATCH:]
            if rest:
                self._cursors[cid] = rest
            else:
                self._cursors.pop(cid, None)
                cid = 0 if not rest else cid
        return {"ok": 1, "cursor": {"nextBatch": batch,
                                    "id": cid if rest else 0,
                                    "ns": "seaweedfs.filemeta"}}

    def _delete(self, cmd: dict) -> dict:
        n = 0
        with self._dblock:
            for d in cmd.get("deletes", []):
                q = d["q"]
                keep = [doc for doc in self.docs
                        if not self._match(doc, q)]
                n += len(self.docs) - len(keep)
                self.docs = keep
        return {"ok": 1, "n": n}
