"""S3 hardening coverage (round-1 verdict item 8): per-request
authorization, canned ACLs, bucket policies, presigned URLs (incl. expiry),
the circuit breaker, bucket quotas, and stale-upload cleanup — modeled on
the surfaces the reference gates through s3acl/, policy/,
s3api_circuit_breaker.go and the s3.* shell commands."""

import io
import json
import socket
import time
import urllib.parse

import pytest
import requests

from seaweedfs_tpu.pb import filer_pb2, rpc
from seaweedfs_tpu.s3api.auth import Identity
from seaweedfs_tpu.s3api.server import S3Server
from seaweedfs_tpu.s3api.sigv4_client import presign_url, sign_request
from seaweedfs_tpu.server.filer import FilerServer
from seaweedfs_tpu.server.master import MasterServer
from seaweedfs_tpu.server.volume import VolumeServer
from seaweedfs_tpu.shell.env import CommandEnv
from seaweedfs_tpu.shell.registry import run_command

ADMIN = Identity("admin", "AKADMIN", "SKADMIN")            # implicit Admin
READER = Identity("reader", "AKREAD", "SKREAD", ["Read", "List"])
NOBODY = Identity("nobody", "AKNONE", "SKNONE", [])


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


@pytest.fixture(scope="module")
def stack(tmp_path_factory):
    mport = _free_port()
    master = MasterServer(ip="localhost", port=mport, volume_size_limit_mb=64)
    master.start(vacuum_interval=3600)
    vsrv = VolumeServer(directories=[str(tmp_path_factory.mktemp("vol"))],
                        master=f"localhost:{mport}", ip="localhost",
                        port=_free_port(), pulse_seconds=1)
    vsrv.start()
    fsrv = FilerServer(ip="localhost", port=_free_port(),
                       master=f"localhost:{mport}", chunk_size=32 * 1024)
    fsrv.start()
    s3 = S3Server(port=_free_port(), filer=fsrv.address,
                  identities=[ADMIN, READER, NOBODY])
    s3.start()
    deadline = time.time() + 10
    while time.time() < deadline and not master.topo.nodes:
        time.sleep(0.05)
    yield master, fsrv, s3
    s3.stop()
    fsrv.stop()
    vsrv.stop()
    master.stop()
    rpc.reset_channels()


def _req(method, url, ident, body=b"", headers=None, **kw):
    h = sign_request(method, url, body, ident.access_key, ident.secret_key)
    h.update(headers or {})
    return requests.request(method, url, data=body or None, headers=h,
                            timeout=30, **kw)


# -- authorization ----------------------------------------------------------

def test_identity_action_authorization(stack):
    *_, s3 = stack
    base = f"http://localhost:{s3.port}"
    assert _req("PUT", f"{base}/authz", ADMIN).status_code == 200
    assert _req("PUT", f"{base}/authz/a.txt", ADMIN,
                b"data").status_code == 200

    # Read identity: GET ok, PUT/DELETE denied, bucket create denied
    assert _req("GET", f"{base}/authz/a.txt", READER).status_code == 200
    assert _req("PUT", f"{base}/authz/b.txt", READER,
                b"x").status_code == 403
    assert _req("DELETE", f"{base}/authz/a.txt", READER).status_code == 403
    assert _req("PUT", f"{base}/newbucket", READER).status_code == 403

    # empty-actions identity: authenticated but can do nothing
    assert _req("GET", f"{base}/authz/a.txt", NOBODY).status_code == 403

    # anonymous fully denied on a private bucket
    assert requests.get(f"{base}/authz/a.txt", timeout=30).status_code == 403


# -- ACLs -------------------------------------------------------------------

def test_canned_acl_public_read(stack):
    *_, s3 = stack
    base = f"http://localhost:{s3.port}"
    assert _req("PUT", f"{base}/aclbkt", ADMIN,
                headers={"x-amz-acl": "public-read"}).status_code == 200
    assert _req("PUT", f"{base}/aclbkt/pub.txt", ADMIN,
                b"public body").status_code == 200

    # anonymous read allowed, write still denied
    r = requests.get(f"http://localhost:{s3.port}/aclbkt/pub.txt", timeout=30)
    assert r.status_code == 200 and r.content == b"public body"
    assert requests.put(f"{base}/aclbkt/nope.txt", data=b"x",
                        timeout=30).status_code == 403

    # GET ?acl renders the AllUsers READ grant
    r = _req("GET", f"{base}/aclbkt?acl", ADMIN)
    assert r.status_code == 200 and "AllUsers" in r.text

    # PUT ?acl flips it back to private -> anonymous read now denied
    assert _req("PUT", f"{base}/aclbkt?acl", ADMIN,
                headers={"x-amz-acl": "private"}).status_code == 200
    assert requests.get(f"{base}/aclbkt/pub.txt", timeout=30).status_code == 403

    # bad canned acl rejected
    assert _req("PUT", f"{base}/aclbkt?acl", ADMIN,
                headers={"x-amz-acl": "lol"}).status_code == 400


# -- bucket policy ----------------------------------------------------------

def test_bucket_policy(stack):
    *_, s3 = stack
    base = f"http://localhost:{s3.port}"
    assert _req("PUT", f"{base}/polbkt", ADMIN).status_code == 200
    assert _req("PUT", f"{base}/polbkt/doc.txt", ADMIN,
                b"policy body").status_code == 200

    # no policy yet
    assert _req("GET", f"{base}/polbkt?policy", ADMIN).status_code == 404

    policy = {"Version": "2012-10-17", "Statement": [
        {"Effect": "Allow", "Principal": "*",
         "Action": ["s3:GetObject"],
         "Resource": "arn:aws:s3:::polbkt/*"},
        {"Effect": "Deny", "Principal": {"AWS": ["AKREAD"]},
         "Action": "s3:GetObject",
         "Resource": "arn:aws:s3:::polbkt/*"},
    ]}
    r = _req("PUT", f"{base}/polbkt?policy", ADMIN,
             json.dumps(policy).encode())
    assert r.status_code == 204

    # policy makes objects world-readable...
    assert requests.get(f"{base}/polbkt/doc.txt", timeout=30).status_code == 200
    # ...but the explicit Deny beats READER's own Read grant
    assert _req("GET", f"{base}/polbkt/doc.txt", READER).status_code == 403
    # malformed policy rejected
    assert _req("PUT", f"{base}/polbkt?policy", ADMIN,
                b"{not json").status_code == 400
    # delete restores privacy
    assert _req("DELETE", f"{base}/polbkt?policy", ADMIN).status_code == 204
    assert requests.get(f"{base}/polbkt/doc.txt", timeout=30).status_code == 403
    assert _req("GET", f"{base}/polbkt/doc.txt", READER).status_code == 200


# -- presigned URLs ---------------------------------------------------------

def test_presigned_urls(stack):
    *_, s3 = stack
    base = f"http://localhost:{s3.port}"
    assert _req("PUT", f"{base}/presig", ADMIN).status_code == 200
    assert _req("PUT", f"{base}/presig/p.txt", ADMIN,
                b"presigned!").status_code == 200

    url = presign_url("GET", f"{base}/presig/p.txt", "AKADMIN", "SKADMIN")
    r = requests.get(url, timeout=30)
    assert r.status_code == 200 and r.content == b"presigned!"

    # tampered signature rejected
    bad = url.replace("X-Amz-Signature=", "X-Amz-Signature=0")
    assert requests.get(bad, timeout=30).status_code == 403

    # expired URL rejected
    old = time.gmtime(time.time() - 7200)
    expired = presign_url("GET", f"{base}/presig/p.txt", "AKADMIN",
                          "SKADMIN", expires=60, amz_now=old)
    r = requests.get(expired, timeout=30)
    assert r.status_code == 403 and "expired" in r.text.lower()

    # out-of-range expiry rejected
    weird = presign_url("GET", f"{base}/presig/p.txt", "AKADMIN",
                        "SKADMIN", expires=700000)
    assert requests.get(weird, timeout=30).status_code == 403


def test_bucket_recreate_preserves_attributes(stack):
    """PUT on an existing bucket must not wipe ACL/policy/quota (CreateEntry
    upserts in the filer, so the handler short-circuits)."""
    *_, s3 = stack
    base = f"http://localhost:{s3.port}"
    assert _req("PUT", f"{base}/keepbkt", ADMIN,
                headers={"x-amz-acl": "public-read"}).status_code == 200
    # re-issue CreateBucket (SDKs retry this routinely)
    assert _req("PUT", f"{base}/keepbkt", ADMIN).status_code == 200
    entry = s3.bucket_entry("keepbkt")
    assert entry.extended.get("Seaweed-X-Amz-Acl") == b"public-read"


def test_presigned_encoded_key_and_missing_expires(stack):
    *_, s3 = stack
    base = f"http://localhost:{s3.port}"
    assert _req("PUT", f"{base}/encbkt", ADMIN).status_code == 200
    key = "dir with space/obj+plus.txt"
    quoted = urllib.parse.quote(key)
    assert _req("PUT", f"{base}/encbkt/{quoted}", ADMIN,
                b"enc body").status_code == 200
    url = presign_url("GET", f"{base}/encbkt/{quoted}", "AKADMIN", "SKADMIN")
    r = requests.get(url, timeout=30)
    assert r.status_code == 200 and r.content == b"enc body"

    # presigned URL missing X-Amz-Expires must be rejected, not eternal
    no_exp = "&".join(p for p in url.split("?", 1)[1].split("&")
                      if not p.startswith("X-Amz-Expires="))
    r = requests.get(url.split("?", 1)[0] + "?" + no_exp, timeout=30)
    assert r.status_code == 403


# -- circuit breaker --------------------------------------------------------

def test_circuit_breaker(stack):
    *_, s3 = stack
    base = f"http://localhost:{s3.port}"
    assert _req("PUT", f"{base}/cbbkt", ADMIN).status_code == 200
    assert _req("PUT", f"{base}/cbbkt/x.txt", ADMIN, b"cb").status_code == 200

    s3.circuit_breaker.load({
        "global": {"enabled": True, "actions": {"Write:Count": 0}},
        "buckets": {"cbbkt": {"enabled": True,
                              "actions": {"Read:Count": 0}}}})
    try:
        r = _req("PUT", f"{base}/cbbkt/y.txt", ADMIN, b"blocked")
        # ISSUE 8 satellite: breaker overload answers the spec-shaped
        # SlowDown (what SDK retry policies classify as throttling),
        # with a Retry-After hint and a resolvable RequestId
        assert r.status_code == 503 and "SlowDown" in r.text
        assert int(r.headers["Retry-After"]) >= 1
        assert _req("GET", f"{base}/cbbkt/x.txt", ADMIN).status_code == 503
        # other buckets only hit the global Write limit, reads still fine
        assert _req("GET", f"{base}/authz/a.txt", ADMIN).status_code == 200
    finally:
        s3.circuit_breaker.load({"global": {"enabled": False}})
    assert _req("PUT", f"{base}/cbbkt/y.txt", ADMIN, b"ok").status_code == 200


def test_circuit_breaker_shell_roundtrip(stack):
    _, fsrv, s3 = stack
    env = CommandEnv("localhost:0", filer=fsrv.address)
    out = io.StringIO()
    code = run_command(
        env, "s3.circuitbreaker -global -enable "
             "-actions=Read:Count=50,Write:MB=16 -apply", out)
    assert code == 0, out.getvalue()
    from seaweedfs_tpu.s3api.circuit_breaker import load_filer_config

    conf = load_filer_config(s3.stub())
    assert conf["global"]["enabled"] is True
    assert conf["global"]["actions"]["Read:Count"] == 50
    s3.circuit_breaker.load(conf)
    assert s3.circuit_breaker.enabled
    assert s3.circuit_breaker.global_limits["Write:MB"] == 16 << 20
    # cleanup for other tests
    run_command(env, "s3.circuitbreaker -delete -apply", io.StringIO())
    s3.circuit_breaker.load({"global": {"enabled": False}})


# -- bucket quota -----------------------------------------------------------

def test_bucket_quota_enforcement(stack):
    _, fsrv, s3 = stack
    base = f"http://localhost:{s3.port}"
    assert _req("PUT", f"{base}/qbkt", ADMIN).status_code == 200
    assert _req("PUT", f"{base}/qbkt/big.bin", ADMIN,
                b"z" * 4096).status_code == 200

    env = CommandEnv("localhost:0", filer=fsrv.address)
    out = io.StringIO()
    assert run_command(env, "s3.bucket.quota -name=qbkt -sizeMB=0", out) == 0
    # 0MB quota -> no quota; set 1 byte via direct entry edit is ugly, use
    # sizeMB rounding: set quota to 1MB then overfill check via small quota
    stub = s3.stub()
    resp = stub.LookupDirectoryEntry(filer_pb2.LookupDirectoryEntryRequest(
        directory="/buckets", name="qbkt"), timeout=10)
    entry = resp.entry
    entry.quota = 1024  # 1KB — already over
    stub.UpdateEntry(filer_pb2.UpdateEntryRequest(
        directory="/buckets", entry=entry), timeout=10)

    out = io.StringIO()
    assert run_command(env, "s3.bucket.quota.check -apply", out) == 0
    assert "read-only" in out.getvalue()
    # writes now rejected, reads fine
    assert _req("PUT", f"{base}/qbkt/more.bin", ADMIN,
                b"no").status_code == 403
    assert _req("GET", f"{base}/qbkt/big.bin", ADMIN).status_code == 200

    # raise the quota -> check flips it back to writable
    resp = stub.LookupDirectoryEntry(filer_pb2.LookupDirectoryEntryRequest(
        directory="/buckets", name="qbkt"), timeout=10)
    entry = resp.entry
    entry.quota = 100 << 20
    stub.UpdateEntry(filer_pb2.UpdateEntryRequest(
        directory="/buckets", entry=entry), timeout=10)
    out = io.StringIO()
    assert run_command(env, "s3.bucket.quota.check -apply", out) == 0
    assert _req("PUT", f"{base}/qbkt/more.bin", ADMIN,
                b"yes").status_code == 200


# -- stale multipart cleanup ------------------------------------------------

def test_s3_clean_uploads(stack):
    _, fsrv, s3 = stack
    base = f"http://localhost:{s3.port}"
    assert _req("PUT", f"{base}/upbkt", ADMIN).status_code == 200
    r = _req("POST", f"{base}/upbkt/file.bin?uploads", ADMIN)
    assert r.status_code == 200
    upload_id = r.text.split("<UploadId>")[1].split("</UploadId>")[0]

    # backdate the upload scratch dir
    stub = s3.stub()
    resp = stub.LookupDirectoryEntry(filer_pb2.LookupDirectoryEntryRequest(
        directory="/buckets/.uploads", name=upload_id), timeout=10)
    entry = resp.entry
    entry.attributes.crtime = int(time.time()) - 7200
    entry.attributes.mtime = entry.attributes.crtime
    stub.UpdateEntry(filer_pb2.UpdateEntryRequest(
        directory="/buckets/.uploads", entry=entry), timeout=10)

    env = CommandEnv("localhost:0", filer=fsrv.address)
    out = io.StringIO()
    assert run_command(env, "s3.clean.uploads -timeAgo=1h", out) == 0
    assert upload_id in out.getvalue()
    import grpc as _grpc

    with pytest.raises(_grpc.RpcError):
        stub.LookupDirectoryEntry(filer_pb2.LookupDirectoryEntryRequest(
            directory="/buckets/.uploads", name=upload_id), timeout=10)


# -- legacy signature v2 ----------------------------------------------------

def test_sigv2_header_and_presigned(stack):
    from seaweedfs_tpu.s3api.sigv4_client import presign_url_v2, sign_request_v2

    *_, s3 = stack
    base = f"http://localhost:{s3.port}"
    h = sign_request_v2("PUT", f"{base}/v2bkt", "AKADMIN", "SKADMIN")
    assert requests.put(f"{base}/v2bkt", headers=h, timeout=30).status_code == 200
    body = b"v2 signed payload"
    h = sign_request_v2("PUT", f"{base}/v2bkt/f.bin", "AKADMIN", "SKADMIN")
    assert requests.put(f"{base}/v2bkt/f.bin", data=body, headers=h,
                        timeout=30).status_code == 200
    h = sign_request_v2("GET", f"{base}/v2bkt/f.bin", "AKADMIN", "SKADMIN")
    r = requests.get(f"{base}/v2bkt/f.bin", headers=h, timeout=30)
    assert r.status_code == 200 and r.content == body

    # wrong secret rejected
    h = sign_request_v2("GET", f"{base}/v2bkt/f.bin", "AKADMIN", "WRONG")
    assert requests.get(f"{base}/v2bkt/f.bin", headers=h,
                        timeout=30).status_code == 403

    # subresources are part of the signed resource (?acl)
    h = sign_request_v2("GET", f"{base}/v2bkt?acl", "AKADMIN", "SKADMIN")
    r = requests.get(f"{base}/v2bkt?acl", headers=h, timeout=30)
    assert r.status_code == 200 and "AccessControlPolicy" in r.text

    # a correctly-signed but stale request is rejected (15-min window)
    from seaweedfs_tpu.s3api.sigv4_client import _v2_sign, _v2_string_to_sign

    old = "Mon, 01 Jan 2024 00:00:00 GMT"
    sig = _v2_sign("SKADMIN",
                   _v2_string_to_sign("GET", "/v2bkt/f.bin", "", old))
    r = requests.get(f"{base}/v2bkt/f.bin",
                     headers={"Date": old,
                              "Authorization": f"AWS AKADMIN:{sig}"},
                     timeout=30)
    assert r.status_code == 403 and "expired" in r.text.lower()

    # presigned v2 works and expires
    url = presign_url_v2("GET", f"{base}/v2bkt/f.bin", "AKADMIN", "SKADMIN")
    r = requests.get(url, timeout=30)
    assert r.status_code == 200 and r.content == body
    stale = presign_url_v2("GET", f"{base}/v2bkt/f.bin", "AKADMIN",
                           "SKADMIN", expires=-10)
    r = requests.get(stale, timeout=30)
    assert r.status_code == 403 and "expired" in r.text.lower()


def test_s3_range_416_and_request_id(stack):
    *_, s3 = stack
    base = f"http://localhost:{s3.port}"
    assert _req("PUT", f"{base}/rngbkt", ADMIN).status_code == 200
    assert _req("PUT", f"{base}/rngbkt/o.bin", ADMIN,
                b"0123456789").status_code == 200
    r = _req("GET", f"{base}/rngbkt/o.bin", ADMIN,
             headers={"Range": "bytes=100-200"})
    assert r.status_code == 416 and "InvalidRange" in r.text
    r = _req("GET", f"{base}/rngbkt/o.bin", ADMIN)
    assert r.status_code == 200
    assert r.headers.get("x-amz-request-id")


def test_s3_conditional_get_roundtrip(stack):
    """ISSUE 9 conformance satellite: the S3 gateway forwards the
    caller's validators to the filer and passes the RFC 7232/7233
    verdict back — a requests/boto-style round trip sees spec-shaped
    304/206/200 with quoted ETags and weak-vs-strong comparison."""
    *_, s3 = stack
    base = f"http://localhost:{s3.port}"
    body = b"conditional get body " * 64
    assert _req("PUT", f"{base}/condbkt", ADMIN).status_code == 200
    assert _req("PUT", f"{base}/condbkt/o.bin", ADMIN,
                body).status_code == 200
    put_etag = _req("PUT", f"{base}/condbkt/o2.bin", ADMIN,
                    body).headers["ETag"]
    g = _req("GET", f"{base}/condbkt/o.bin", ADMIN)
    assert g.status_code == 200 and g.content == body
    etag = g.headers["ETag"]
    assert etag.startswith('"') and etag.endswith('"'), etag
    # one entity-tag across the whole surface: a client revalidating
    # with its PUT-returned ETag gets the 304 (GET/HEAD/PUT agree on
    # the stored whole-body md5)
    assert etag == _req("HEAD", f"{base}/condbkt/o.bin",
                        ADMIN).headers["ETag"]
    r = _req("GET", f"{base}/condbkt/o2.bin", ADMIN,
             headers={"If-None-Match": put_etag})
    assert r.status_code == 304, (put_etag, r.status_code)
    # If-None-Match: exact, weak, list and * all 304 (weak comparison);
    # the 304 carries the ETag and an empty body
    for inm in (etag, f"W/{etag}", f'"zz", {etag}', "*"):
        r = _req("GET", f"{base}/condbkt/o.bin", ADMIN,
                 headers={"If-None-Match": inm})
        assert r.status_code == 304, (inm, r.status_code)
        assert r.headers.get("ETag") == etag
        assert r.content == b""
    r = _req("GET", f"{base}/condbkt/o.bin", ADMIN,
             headers={"If-None-Match": '"zz"'})
    assert r.status_code == 200 and r.content == body
    # If-Range: a strong match honors the Range (206), a weak tag or a
    # mismatch serves the full 200 (never an error)
    r = _req("GET", f"{base}/condbkt/o.bin", ADMIN,
             headers={"Range": "bytes=0-9", "If-Range": etag})
    assert r.status_code == 206 and r.content == body[:10]
    assert r.headers["Content-Range"] == f"bytes 0-9/{len(body)}"
    for stale in (f"W/{etag}", '"zz"'):
        r = _req("GET", f"{base}/condbkt/o.bin", ADMIN,
                 headers={"Range": "bytes=0-9", "If-Range": stale})
        assert r.status_code == 200 and r.content == body
    # If-Modified-Since consulted only without If-None-Match
    fresh = time.strftime("%a, %d %b %Y %H:%M:%S GMT",
                          time.gmtime(time.time() + 3600))
    r = _req("GET", f"{base}/condbkt/o.bin", ADMIN,
             headers={"If-Modified-Since": fresh})
    assert r.status_code == 304
    r = _req("GET", f"{base}/condbkt/o.bin", ADMIN,
             headers={"If-None-Match": '"zz"',
                      "If-Modified-Since": fresh})
    assert r.status_code == 200 and r.content == body


def test_s3_streamed_put_incomplete_body(stack):
    """A body shorter than Content-Length must 400 (IncompleteBody), not
    store a truncated object (open-mode gateway streams unsigned PUTs)."""
    import socket as sk

    _, fsrv, s3 = stack
    # open-mode gateway (no identities) so the unsigned path streams
    s3_open = S3Server(port=_free_port(), filer=fsrv.address)
    s3_open.start()
    try:
        base = f"http://localhost:{s3_open.port}"
        assert requests.put(f"{base}/incbkt", timeout=10).status_code == 200
        conn = sk.create_connection(("localhost", s3_open.port), timeout=10)
        conn.sendall(b"PUT /incbkt/short.bin HTTP/1.1\r\n"
                     b"Host: localhost\r\nContent-Length: 100\r\n\r\n"
                     b"only-ten-b")
        conn.shutdown(sk.SHUT_WR)
        resp = b""
        while True:
            piece = conn.recv(4096)
            if not piece:
                break
            resp += piece
        conn.close()
        assert b"IncompleteBody" in resp, resp[:200]
        # nothing stored
        r = requests.get(f"{base}/incbkt/short.bin", timeout=10)
        assert r.status_code == 404
    finally:
        s3_open.stop()


def test_s3_chunked_te_put_roundtrip(stack):
    _, fsrv, _ = stack
    s3_open = S3Server(port=_free_port(), filer=fsrv.address)
    s3_open.start()
    try:
        base = f"http://localhost:{s3_open.port}"
        assert requests.put(f"{base}/tebkt", timeout=10).status_code == 200
        payload = b"chunked transfer to s3 " * 4096

        def gen():
            for off in range(0, len(payload), 8192):
                yield payload[off:off + 8192]

        s = requests.Session()
        r = s.put(f"{base}/tebkt/o.bin", data=gen(), timeout=30)
        assert r.status_code == 200, r.text
        r = s.get(f"{base}/tebkt/o.bin", timeout=30)
        assert r.status_code == 200 and r.content == payload
    finally:
        s3_open.stop()


# -- QoS / spec-shaped errors (ISSUE 8) -------------------------------------

def _parse_error_xml(body: bytes) -> dict:
    """Parse an S3 error body the way botocore's RestXMLParser does:
    <Error> root, Code/Message/Resource/RequestId children. A body this
    parse rejects is a body real SDKs fail hard on instead of backing
    off."""
    import xml.etree.ElementTree as ET

    root = ET.fromstring(body)
    assert root.tag == "Error", root.tag
    return {el.tag: (el.text or "") for el in root}


def test_error_xml_spec_shaped_and_trace_resolvable(stack):
    """ISSUE 8 satellite: overload answers carry the full spec shape —
    Code, Message, Resource, RequestId — and the RequestId IS the trace
    id, resolvable through /debug/traces to the per-plane breakdown."""
    *_, s3 = stack
    base = f"http://localhost:{s3.port}"
    assert _req("PUT", f"{base}/xmlbkt", ADMIN).status_code == 200
    s3.circuit_breaker.load({
        "global": {"enabled": True, "actions": {"Write:Count": 0}}})
    try:
        r = _req("PUT", f"{base}/xmlbkt/z.txt", ADMIN, b"shed")
        assert r.status_code == 503
        err = _parse_error_xml(r.content)
        assert err["Code"] == "SlowDown"
        assert "reduce" in err["Message"].lower()
        assert err["Resource"] == "/xmlbkt/z.txt"
        assert err["RequestId"]
        assert int(r.headers["Retry-After"]) >= 1
        # the RequestId is the trace handle: the gateway's own span for
        # this rejected request is one /debug/traces lookup away
        assert err["RequestId"] == r.headers.get("X-Trace-Id")
        # the debug plane needs an Admin identity while IAM is on
        dbg = _req("GET",
                   f"{base}/debug/traces?trace={err['RequestId']}", ADMIN)
        assert dbg.status_code == 200
        assert dbg.json().get("spans"), "rejection trace not resolvable"
    finally:
        s3.circuit_breaker.load({"global": {"enabled": False}})
    # a plain data-plane error parses with the same shape (NoSuchKey
    # class errors ride _error too)
    r = _req("GET", f"{base}/xmlbkt/never-was.txt", ADMIN)
    assert r.status_code == 404
    err = _parse_error_xml(r.content)
    assert err["Code"] and err["RequestId"] and \
        err["Resource"] == "/xmlbkt/never-was.txt"


def test_s3_tenant_admission_slowdown(stack, monkeypatch):
    """ISSUE 8: per-tenant token-bucket admission at the S3 ingress —
    the tenant keyed by its ACCESS KEY is capped; the excess sheds as
    503 SlowDown with an honest Retry-After; other tenants and the
    anonymous bucket budget are untouched."""
    *_, s3 = stack
    base = f"http://localhost:{s3.port}"
    assert _req("PUT", f"{base}/qosbkt", ADMIN).status_code == 200
    assert _req("PUT", f"{base}/qosbkt/a.txt", ADMIN,
                b"x").status_code == 200
    monkeypatch.setenv(
        "SWFS_QOS_TENANT_OVERRIDES",
        '{"ak:AKREAD": {"rps": 1, "burst": 2}}')
    s3.qos_admission.refresh_config()
    try:
        codes = [_req("GET", f"{base}/qosbkt/a.txt", READER).status_code
                 for _ in range(6)]
        assert codes.count(503) >= 3, codes
        assert 200 in codes  # burst admitted before the cap bit
        r = _req("GET", f"{base}/qosbkt/a.txt", READER)
        assert r.status_code == 503
        err = _parse_error_xml(r.content)
        assert err["Code"] == "SlowDown" and err["RequestId"]
        assert int(r.headers["Retry-After"]) >= 1
        # the rejection is on the admission record with its trace id
        rej = s3.qos_admission.recent_rejections()[-1]
        assert rej["tenant"] == "ak:AKREAD"
        assert rej["traceId"] == err["RequestId"]
        # a different identity (different tenant bucket) is unaffected
        assert _req("GET", f"{base}/qosbkt/a.txt",
                    ADMIN).status_code == 200
    finally:
        monkeypatch.delenv("SWFS_QOS_TENANT_OVERRIDES")
        s3.qos_admission.refresh_config()


def test_s3_internal_leg_not_double_charged(stack, monkeypatch):
    """ISSUE 8 review fix: the gateway's filer legs carry
    X-Swfs-Qos-Charged, so a tenant's budget is billed ONCE (at the S3
    ingress) — previously the internal filer hop charged the same
    col:<bucket> budget again, halving every tenant's effective rate
    and surfacing the second 429 as a 500. Direct filer traffic on the
    same collection still sheds."""
    _, fsrv, s3 = stack
    base = f"http://localhost:{s3.port}"
    assert _req("PUT", f"{base}/chgbkt", ADMIN).status_code == 200
    assert _req("PUT", f"{base}/chgbkt/a.txt", ADMIN,
                b"x").status_code == 200
    monkeypatch.setenv("SWFS_QOS_TENANT_OVERRIDES",
                       '{"col:chgbkt": {"rps": 0.001, "burst": 2}}')
    fsrv.qos_admission.refresh_config()
    try:
        # the collection's filer budget is 2 requests then dry — but
        # gateway reads are not billed on the internal leg, so every
        # one of these succeeds
        codes = [_req("GET", f"{base}/chgbkt/a.txt", ADMIN).status_code
                 for _ in range(6)]
        assert codes == [200] * 6, codes
        # a direct filer client drains that same budget and sheds 429
        direct = [requests.get(
            f"http://{fsrv.address}/buckets/chgbkt/a.txt",
            timeout=10).status_code for _ in range(4)]
        assert 429 in direct and 200 in direct, direct
    finally:
        monkeypatch.delenv("SWFS_QOS_TENANT_OVERRIDES")
        fsrv.qos_admission.refresh_config()


def test_backend_throttle_maps_to_slowdown():
    """A 429/503 from the backing filer is throttling, not a server
    fault: it must surface as spec-shaped SlowDown carrying the
    backend's Retry-After, never InternalError."""
    from seaweedfs_tpu.s3api.server import _backend_throttled

    class _Resp:
        headers = {"Retry-After": "7"}

    err = _backend_throttled(_Resp(), "filer GET")
    assert err.status == 503 and err.code == "SlowDown"
    assert err.retry_after_s == 7.0
    _Resp.headers = {}
    assert _backend_throttled(_Resp(), "filer PUT").retry_after_s == 1.0
