"""Volume engine: write/read/delete, dedup, reload, torn-tail repair, vacuum."""

import os

import pytest

from seaweedfs_tpu.storage import types
from seaweedfs_tpu.storage.needle import Needle
from seaweedfs_tpu.storage.super_block import ReplicaPlacement
from seaweedfs_tpu.storage.ttl import TTL
from seaweedfs_tpu.storage.volume import (
    CookieMismatch,
    DeletedError,
    NotFoundError,
    Volume,
)


def make_needle(nid, data, cookie=0xABC, **kw):
    return Needle.create(nid, cookie, data, last_modified=1_700_000_000, **kw)


@pytest.fixture
def vol(tmp_path):
    v = Volume(str(tmp_path), "", 1)
    yield v
    v.close()


def test_write_read_roundtrip(vol):
    offset, size, unchanged = vol.write_needle(make_needle(1, b"hello"))
    assert not unchanged and offset == 8  # right after superblock
    n = vol.read_needle(1)
    assert n.data == b"hello"
    assert n.cookie == 0xABC


def test_cookie_check(vol):
    vol.write_needle(make_needle(2, b"data"))
    with pytest.raises(CookieMismatch):
        vol.read_needle(2, cookie=0x999)
    assert vol.read_needle(2, cookie=0xABC).data == b"data"


def test_dedup_unchanged_write(vol):
    vol.write_needle(make_needle(3, b"same"))
    size_before = vol.data_size()
    _, _, unchanged = vol.write_needle(make_needle(3, b"same"))
    assert unchanged
    assert vol.data_size() == size_before


def test_overwrite_and_delete(vol):
    vol.write_needle(make_needle(4, b"v1"))
    vol.write_needle(make_needle(4, b"version2"))
    assert vol.read_needle(4).data == b"version2"
    freed = vol.delete_needle(4, cookie=0xABC)
    assert freed > 0
    with pytest.raises((NotFoundError, DeletedError)):
        vol.read_needle(4)
    assert vol.delete_needle(4) == 0  # idempotent


def test_write_cookie_mismatch_rejected(vol):
    vol.write_needle(make_needle(5, b"a", cookie=1))
    with pytest.raises(CookieMismatch):
        vol.write_needle(make_needle(5, b"b", cookie=2))


def test_reload_from_disk(tmp_path):
    v = Volume(str(tmp_path), "col", 7, replica_placement=ReplicaPlacement.parse("001"))
    for i in range(1, 20):
        v.write_needle(make_needle(i, f"data-{i}".encode()))
    v.delete_needle(5)
    v.close()

    v2 = Volume(str(tmp_path), "col", 7)
    assert v2.super_block.replica_placement == ReplicaPlacement.parse("001")
    for i in range(1, 20):
        if i == 5:
            with pytest.raises(KeyError):
                v2.read_needle(i)
        else:
            assert v2.read_needle(i).data == f"data-{i}".encode()
    v2.close()


def test_torn_tail_repair(tmp_path):
    v = Volume(str(tmp_path), "", 9)
    for i in range(1, 6):
        v.write_needle(make_needle(i, b"x" * 100))
    good_size = v.data_size()
    v.write_needle(make_needle(6, b"y" * 500))
    v.close()
    # tear the last record halfway
    base = v.file_name()
    with open(base + ".dat", "r+b") as f:
        f.truncate(good_size + 37)
    v2 = Volume(str(tmp_path), "", 9)
    assert v2.data_size() == good_size
    for i in range(1, 6):
        assert v2.read_needle(i).data == b"x" * 100
    with pytest.raises(KeyError):
        v2.read_needle(6)
    # volume still writable after repair
    v2.write_needle(make_needle(6, b"z" * 20))
    assert v2.read_needle(6).data == b"z" * 20
    v2.close()


def test_vacuum_compaction(tmp_path):
    v = Volume(str(tmp_path), "", 11)
    for i in range(1, 31):
        v.write_needle(make_needle(i, bytes([i]) * 1000))
    for i in range(1, 21):
        v.delete_needle(i)
    v.write_needle(make_needle(50, b"late"))
    assert v.garbage_level() > 0.5
    size_before = v.data_size()
    v.compact()
    # a write that lands *during* compaction must survive the commit
    v.write_needle(make_needle(51, b"during-compact"))
    v.delete_needle(30)
    v.commit_compact()
    assert v.data_size() < size_before
    assert v.super_block.compaction_revision == 1
    for i in range(21, 30):
        assert v.read_needle(i).data == bytes([i]) * 1000
    assert v.read_needle(50).data == b"late"
    assert v.read_needle(51).data == b"during-compact"
    for i in list(range(1, 21)) + [30]:
        with pytest.raises(KeyError):
            v.read_needle(i)
    # compacted volume reloads cleanly
    v.close()
    v2 = Volume(str(tmp_path), "", 11)
    assert v2.read_needle(51).data == b"during-compact"
    v2.close()


def test_ttl_expiry(tmp_path):
    v = Volume(str(tmp_path), "", 13, ttl=TTL.parse("1m"))
    n = make_needle(1, b"short-lived", ttl=TTL.parse("1m"))
    n.last_modified = 1_000_000  # long past
    n.set_flag(0x10)
    v.write_needle(n)
    with pytest.raises(NotFoundError):
        v.read_needle(1)
    v.close()


def test_needle_map_counters(vol):
    vol.write_needle(make_needle(1, b"aaaa"))
    vol.write_needle(make_needle(2, b"bbbb"))
    vol.delete_needle(1)
    assert vol.file_count() == 1
    assert vol.deleted_count() == 1
    assert vol.nm.max_file_key == 2


def test_destroy(tmp_path):
    v = Volume(str(tmp_path), "", 21)
    v.write_needle(make_needle(1, b"x"))
    base = v.file_name()
    assert os.path.exists(base + ".dat")
    v.destroy()
    assert not os.path.exists(base + ".dat")
    assert not os.path.exists(base + ".idx")
