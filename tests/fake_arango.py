"""In-process fake ArangoDB: document CRUD with overwriteMode=replace,
collection create/drop, basic auth, and an AQL endpoint that executes
the filer store's two query templates (list + subtree remove) with
bindVars and small cursor batches so hasMore/PUT-cursor paging runs.
Exercises seaweedfs_tpu/filer/stores/arango_wire.py end to end."""

from __future__ import annotations

import base64
import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

BATCH = 3


class FakeArangoServer:
    def __init__(self, *, username: str = "", password: str = ""):
        self.username, self.password = username, password
        self.collections: dict[str, dict[str, dict]] = {}
        self._cursors: dict[str, list[dict]] = {}
        self._next_cursor = 100
        self._lock = threading.Lock()
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def _body(self) -> dict:
                n = int(self.headers.get("Content-Length") or 0)
                raw = self.rfile.read(n) if n else b""
                return json.loads(raw) if raw else {}

            def _send(self, status: int, doc: dict) -> None:
                payload = json.dumps(doc).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def _authed(self) -> bool:
                if not outer.password:
                    return True
                want = "Basic " + base64.b64encode(
                    f"{outer.username}:{outer.password}".encode()).decode()
                return self.headers.get("Authorization", "") == want

            def _route(self, method: str) -> None:
                if not self._authed():
                    self._send(401, {"error": True, "errorMessage":
                                     "unauthorized"})
                    return
                try:
                    outer._handle(self, method)
                except Exception as e:  # pragma: no cover
                    self._send(500, {"error": True, "errorMessage": str(e)})

            def do_GET(self):
                self._route("GET")

            def do_POST(self):
                self._route("POST")

            def do_PUT(self):
                self._route("PUT")

            def do_DELETE(self):
                self._route("DELETE")

        self._httpd = ThreadingHTTPServer(("localhost", 0), Handler)
        self.port = self._httpd.server_port
        threading.Thread(target=self._httpd.serve_forever,
                         daemon=True).start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()

    # -- routing -----------------------------------------------------------

    def _handle(self, h, method: str) -> None:
        path, _, query = h.path.partition("?")
        parts = [p for p in path.split("/") if p]
        # strip /_db/<name>
        if len(parts) >= 2 and parts[0] == "_db":
            parts = parts[2:]
        body = h._body() if method in ("POST", "PUT") else {}
        with self._lock:
            if parts[:2] == ["_api", "collection"]:
                if method == "GET" and len(parts) == 2:
                    h._send(200, {"result": [{"name": n}
                                             for n in self.collections]})
                    return
                if method == "POST":
                    name = body.get("name", "")
                    if name in self.collections:
                        h._send(409, {"error": True,
                                      "errorMessage": "duplicate name"})
                    else:
                        self.collections[name] = {}
                        h._send(200, {"name": name})
                elif method == "DELETE" and len(parts) == 3:
                    if self.collections.pop(parts[2], None) is None:
                        h._send(404, {"error": True})
                    else:
                        h._send(200, {})
                else:
                    h._send(400, {"error": True})
                return
            if parts[:2] == ["_api", "document"]:
                self._document(h, method, parts[2:], body,
                               "overwriteMode=replace" in query)
                return
            if parts[:2] == ["_api", "cursor"]:
                if method == "POST":
                    self._cursor_start(h, body)
                elif method == "PUT" and len(parts) == 3:
                    self._cursor_next(h, parts[2])
                else:
                    h._send(400, {"error": True})
                return
        h._send(400, {"error": True,
                      "errorMessage": f"unhandled {method} {path}"})

    def _document(self, h, method: str, rest: list, body: dict,
                  replace: bool) -> None:
        if method == "POST" and len(rest) == 1:
            coll = self.collections.get(rest[0])
            if coll is None:
                h._send(404, {"error": True})
                return
            key = body.get("_key", "")
            if key in coll and not replace:
                h._send(409, {"error": True, "errorMessage": "conflict"})
                return
            coll[key] = body
            h._send(201, {"_key": key})
            return
        if len(rest) == 2:
            coll = self.collections.get(rest[0])
            if coll is None or rest[1] not in coll:
                h._send(404, {"error": True})
                return
            if method == "GET":
                h._send(200, coll[rest[1]])
            elif method == "DELETE":
                del coll[rest[1]]
                h._send(200, {})
            else:
                h._send(400, {"error": True})
            return
        h._send(400, {"error": True})

    # -- AQL (the store's two templates only) ------------------------------

    _LIST_RE = re.compile(
        r"FOR d IN @@collection FILTER d\.directory == @dir "
        r"AND d\.name (>=|>) @start AND STARTS_WITH\(d\.name, @prefix\) "
        r"SORT d\.name ASC LIMIT @limit RETURN d")
    _REMOVE_RE = re.compile(
        r"FOR d IN @@collection FILTER d\.directory == @dir OR "
        r"STARTS_WITH\(d\.directory, @sub\) REMOVE d IN @@collection")

    def _cursor_start(self, h, body: dict) -> None:
        query = " ".join(body.get("query", "").split())
        bind = body.get("bindVars", {})
        coll = self.collections.get(bind.get("@collection", ""))
        if coll is None:
            h._send(404, {"error": True, "errorMessage": "no collection"})
            return
        m = self._LIST_RE.fullmatch(query)
        if m:
            op = m.group(1)
            rows = [d for d in coll.values()
                    if d.get("directory") == bind["dir"]
                    and (d.get("name", "") >= bind["start"] if op == ">="
                         else d.get("name", "") > bind["start"])
                    and d.get("name", "").startswith(bind["prefix"])]
            rows.sort(key=lambda d: d.get("name", ""))
            rows = rows[:bind["limit"]]
            self._respond_batched(h, rows)
            return
        if self._REMOVE_RE.fullmatch(query):
            doomed = [k for k, d in coll.items()
                      if d.get("directory") == bind["dir"]
                      or d.get("directory", "").startswith(bind["sub"])]
            for k in doomed:
                del coll[k]
            h._send(201, {"result": [], "hasMore": False,
                          "count": len(doomed)})
            return
        h._send(400, {"error": True,
                      "errorMessage": f"unsupported AQL: {query}"})

    def _respond_batched(self, h, rows: list) -> None:
        first, rest = rows[:BATCH], rows[BATCH:]
        doc: dict = {"result": first, "hasMore": bool(rest)}
        if rest:
            cid = str(self._next_cursor)
            self._next_cursor += 1
            self._cursors[cid] = rest
            doc["id"] = cid
        h._send(201, doc)

    def _cursor_next(self, h, cid: str) -> None:
        rest = self._cursors.get(cid, [])
        batch, rest = rest[:BATCH], rest[BATCH:]
        if rest:
            self._cursors[cid] = rest
        else:
            self._cursors.pop(cid, None)
        doc = {"result": batch, "hasMore": bool(rest)}
        if rest:
            doc["id"] = cid
        h._send(200, doc)
