"""ShardedCoder over the virtual 8-device CPU mesh (conftest forces
XLA_FLAGS=--xla_force_host_platform_device_count=8)."""

import numpy as np
import pytest

from seaweedfs_tpu.ops.rs_cpu import RSCodecCPU
from seaweedfs_tpu.parallel.mesh import ShardedCoder, make_mesh


@pytest.fixture(scope="module")
def mesh():
    return make_mesh()


@pytest.fixture(scope="module")
def coder(mesh):
    return ShardedCoder(10, 4, mesh=mesh)


def test_mesh_has_8_devices(mesh):
    assert mesh.devices.size == 8


def test_sharded_encode_matches_cpu(coder):
    rng = np.random.default_rng(0)
    data = rng.integers(0, 256, size=(10, 5000), dtype=np.uint8)  # odd B
    ref = RSCodecCPU(10, 4).encode_parity(data)
    got = np.asarray(coder.encode_parity(data))
    assert got.shape == (4, 5000)
    assert np.array_equal(got, ref)


def test_sharded_reconstruct(coder):
    rng = np.random.default_rng(1)
    data = rng.integers(0, 256, size=(10, 2048), dtype=np.uint8)
    shards = np.asarray(coder.encode(data))
    survivors = {i: shards[i] for i in range(14) if i not in (1, 4, 10, 12)}
    rebuilt = coder.reconstruct(survivors)
    for i in (1, 4, 10, 12):
        assert np.array_equal(np.asarray(rebuilt[i]), shards[i])


def test_sharded_reconstruct_stacked_matches_dict(coder):
    """Mesh-sharded stacked reconstruct: same contract and bytes as the
    dict path, shuffled caller row order, surplus survivors."""
    rng = np.random.default_rng(7)
    data = rng.integers(0, 256, size=(10, 4096), dtype=np.uint8)
    shards = np.asarray(coder.encode(data))
    lost = (1, 10, 12)  # 11 survivors > k: exercises the zero columns
    pres_ids = tuple(i for i in range(14) if i not in lost)[::-1]
    stacked = np.stack([shards[i] for i in pres_ids])
    mids, rows = coder.reconstruct_stacked(pres_ids, stacked)
    assert mids == lost
    rows = np.asarray(rows)
    for j, i in enumerate(mids):
        assert np.array_equal(rows[j], shards[i])
    # nothing missing
    mids0, rows0 = coder.reconstruct_stacked(tuple(range(14)), shards)
    assert mids0 == () and np.asarray(rows0).shape[0] == 0


def test_parity_checksum_zero_then_nonzero(coder):
    rng = np.random.default_rng(2)
    data = rng.integers(0, 256, size=(10, 1024), dtype=np.uint8)
    shards = np.asarray(coder.encode(data)).copy()
    assert int(np.asarray(coder.parity_checksum(shards))) == 0
    shards[3, 100] ^= 0xFF
    assert int(np.asarray(coder.parity_checksum(shards))) != 0


def test_alt_geometries(mesh):
    for k, m in ((6, 3), (12, 4)):
        c = ShardedCoder(k, m, mesh=mesh)
        rng = np.random.default_rng(k)
        data = rng.integers(0, 256, size=(k, 999), dtype=np.uint8)
        ref = RSCodecCPU(k, m).encode_parity(data)
        assert np.array_equal(np.asarray(c.encode_parity(data)), ref)


def test_graft_entry_single_chip():
    import __graft_entry__ as ge

    fn, args = ge.entry()
    out = np.asarray(fn(*args))
    ref = RSCodecCPU(10, 4).encode_parity(args[0])
    assert np.array_equal(out[10:], ref)
    assert np.array_equal(out[:10], args[0])


def test_graft_dryrun_multichip():
    import __graft_entry__ as ge

    ge.dryrun_multichip(8)


def test_new_coder_resolves_to_mesh():
    """The PRODUCTION coder path (new_coder, used by Store and the EC
    RPC handlers) must ride the mesh whenever >1 device exists — VERDICT
    round 2 #2: multi-chip as a capability of the product, not a demo."""
    from seaweedfs_tpu.models.coder import AutoMeshCoder, new_coder

    c = new_coder(10, 4)
    assert isinstance(c, AutoMeshCoder)
    assert isinstance(c._resolve(), ShardedCoder)
    rng = np.random.default_rng(3)
    data = rng.integers(0, 256, size=(10, 3000), dtype=np.uint8)
    ref = RSCodecCPU(10, 4).encode_parity(data)
    assert np.array_equal(np.asarray(c.encode_parity(data)), ref)


@pytest.mark.slow
def test_generate_ec_files_mesh_bit_identical(tmp_path):
    """generate_ec_files + rebuild_ec_files through the default production
    coder (mesh-sharded on this 8-device suite) are byte-identical to the
    CPU oracle's shard files — odd payload size, different drop set than
    the dryrun's. Minutes of GF math through 8 virtual CPU devices."""
    import __graft_entry__ as ge

    from seaweedfs_tpu.models.coder import new_coder

    ge.ec_file_pipeline_oracle(str(tmp_path), new_coder(10, 4),
                               batch_size=2000, drop=(0, 6, 13),
                               payload_len=351_003, seed=9)


def test_mesh_kernels_bit_identical():
    """xor vs bits per-device formulations agree byte-for-byte on the mesh."""
    import numpy as np

    from seaweedfs_tpu.parallel.mesh import ShardedCoder, make_mesh

    mesh = make_mesh()
    rng = np.random.default_rng(21)
    data = rng.integers(0, 256, size=(10, 4096), dtype=np.uint8)
    out = {}
    for kernel in ("xor", "bits"):
        coder = ShardedCoder(10, 4, mesh=mesh, kernel=kernel)
        shards = np.asarray(coder.encode(data))
        present = {i: shards[i] for i in range(14) if i not in (2, 7, 13)}
        rebuilt = coder.reconstruct(present)
        out[kernel] = (shards, {i: np.asarray(v) for i, v in rebuilt.items()})
        assert int(np.asarray(coder.parity_checksum(shards))) == 0
    np.testing.assert_array_equal(out["xor"][0], out["bits"][0])
    for i in (2, 7, 13):
        np.testing.assert_array_equal(out["xor"][1][i], out["bits"][1][i])


def test_generate_ec_files_mesh_production_ratio_boundary(tmp_path):
    """Mesh-sharded encode across a REAL large-row -> small-row boundary
    at the production 1024:1 block ratio (1GB:1MB scaled to 1MB:1KB —
    the full-constant run needs ~10GB of GF math, ~10 min on this
    1-core box; the boundary/row arithmetic under test is ratio- and
    row-count-exact either way). Payload = 1 full large row + 3 small
    rows + a partial block, so the schedule emits every row kind."""
    import __graft_entry__ as ge

    from seaweedfs_tpu.models.coder import new_coder
    from seaweedfs_tpu.storage.ec_locate import Geometry

    geo = Geometry(large_block=1 << 20, small_block=1 << 10)
    k = geo.data_shards
    payload = (geo.large_block * k          # one full large row
               + geo.small_block * k * 3    # three full small rows
               + 700)                       # partial trailing block
    n_large, n_small = geo.row_counts(payload)
    assert (n_large, n_small) == (1, 4), "payload must cross the boundary"
    ge.ec_file_pipeline_oracle(str(tmp_path), new_coder(10, 4),
                               batch_size=1 << 18, drop=(1, 5, 11),
                               payload_len=payload, seed=31, geo=geo)


def test_geometry_arithmetic_at_true_production_constants():
    """Row/locate arithmetic at the UNSCALED 1GB/1MB constants with
    multi-GB offsets: every byte of a 22GB+ volume must map to exactly
    one (shard, offset) and the mapping must be monotone within a shard
    — the class of bug (32-bit truncation, row mis-count) that shrunken
    geometries can't surface."""
    from seaweedfs_tpu.storage.ec_locate import (
        LARGE_BLOCK_SIZE,
        SMALL_BLOCK_SIZE,
        Geometry,
        locate_data,
    )

    geo = Geometry()
    assert geo.large_block == LARGE_BLOCK_SIZE == 1 << 30
    assert geo.small_block == SMALL_BLOCK_SIZE == 1 << 20
    k = geo.data_shards
    # 2 full large rows + 5 small rows + partial: 21.48GB
    dat_size = 2 * k * geo.large_block + 5 * k * geo.small_block + 12_345
    assert geo.row_counts(dat_size) == (2, 6)
    assert geo.shard_size(dat_size) == \
        2 * geo.large_block + 6 * geo.small_block
    # probe offsets all around the large->small boundary and the tail
    boundary = 2 * k * geo.large_block
    probes = [0, geo.large_block - 1, geo.large_block,
              boundary - 1, boundary, boundary + 1,
              boundary + k * geo.small_block,     # 2nd small row
              dat_size - 12_345, dat_size - 1]
    for off in probes:
        ivs = locate_data(geo, dat_size, off, 1)
        assert len(ivs) == 1, off
        sid, soff = ivs[0].to_shard_id_and_offset(geo)
        assert 0 <= sid < k
        assert 0 <= soff < geo.shard_size(dat_size), (off, soff)
    # a read spanning the boundary covers every byte exactly once
    span = locate_data(geo, dat_size, boundary - 4096, 8192)
    assert sum(iv.size for iv in span) == 8192
    assert any(iv.is_large_block for iv in span)
    assert any(not iv.is_large_block for iv in span)
    # small-row shard offsets land past 2^31: the mapping must stay
    # 64-bit exact (2 large blocks = 2^31, plus the small-row tail)
    iv = locate_data(geo, dat_size, dat_size - 1, 1)[0]
    _, soff = iv.to_shard_id_and_offset(geo)
    assert soff > 2**31 - 1
    assert soff < geo.shard_size(dat_size)
