"""ShardedCoder over the virtual 8-device CPU mesh (conftest forces
XLA_FLAGS=--xla_force_host_platform_device_count=8)."""

import numpy as np
import pytest

from seaweedfs_tpu.ops.rs_cpu import RSCodecCPU
from seaweedfs_tpu.parallel.mesh import ShardedCoder, make_mesh


@pytest.fixture(scope="module")
def mesh():
    return make_mesh()


@pytest.fixture(scope="module")
def coder(mesh):
    return ShardedCoder(10, 4, mesh=mesh)


def test_mesh_has_8_devices(mesh):
    assert mesh.devices.size == 8


def test_sharded_encode_matches_cpu(coder):
    rng = np.random.default_rng(0)
    data = rng.integers(0, 256, size=(10, 5000), dtype=np.uint8)  # odd B
    ref = RSCodecCPU(10, 4).encode_parity(data)
    got = np.asarray(coder.encode_parity(data))
    assert got.shape == (4, 5000)
    assert np.array_equal(got, ref)


def test_sharded_reconstruct(coder):
    rng = np.random.default_rng(1)
    data = rng.integers(0, 256, size=(10, 2048), dtype=np.uint8)
    shards = np.asarray(coder.encode(data))
    survivors = {i: shards[i] for i in range(14) if i not in (1, 4, 10, 12)}
    rebuilt = coder.reconstruct(survivors)
    for i in (1, 4, 10, 12):
        assert np.array_equal(np.asarray(rebuilt[i]), shards[i])


def test_sharded_reconstruct_stacked_matches_dict(coder):
    """Mesh-sharded stacked reconstruct: same contract and bytes as the
    dict path, shuffled caller row order, surplus survivors."""
    rng = np.random.default_rng(7)
    data = rng.integers(0, 256, size=(10, 4096), dtype=np.uint8)
    shards = np.asarray(coder.encode(data))
    lost = (1, 10, 12)  # 11 survivors > k: exercises the zero columns
    pres_ids = tuple(i for i in range(14) if i not in lost)[::-1]
    stacked = np.stack([shards[i] for i in pres_ids])
    mids, rows = coder.reconstruct_stacked(pres_ids, stacked)
    assert mids == lost
    rows = np.asarray(rows)
    for j, i in enumerate(mids):
        assert np.array_equal(rows[j], shards[i])
    # nothing missing
    mids0, rows0 = coder.reconstruct_stacked(tuple(range(14)), shards)
    assert mids0 == () and np.asarray(rows0).shape[0] == 0


def test_parity_checksum_zero_then_nonzero(coder):
    rng = np.random.default_rng(2)
    data = rng.integers(0, 256, size=(10, 1024), dtype=np.uint8)
    shards = np.asarray(coder.encode(data)).copy()
    assert int(np.asarray(coder.parity_checksum(shards))) == 0
    shards[3, 100] ^= 0xFF
    assert int(np.asarray(coder.parity_checksum(shards))) != 0


def test_alt_geometries(mesh):
    for k, m in ((6, 3), (12, 4)):
        c = ShardedCoder(k, m, mesh=mesh)
        rng = np.random.default_rng(k)
        data = rng.integers(0, 256, size=(k, 999), dtype=np.uint8)
        ref = RSCodecCPU(k, m).encode_parity(data)
        assert np.array_equal(np.asarray(c.encode_parity(data)), ref)


def test_graft_entry_single_chip():
    import __graft_entry__ as ge

    fn, args = ge.entry()
    out = np.asarray(fn(*args))
    ref = RSCodecCPU(10, 4).encode_parity(args[0])
    assert np.array_equal(out[10:], ref)
    assert np.array_equal(out[:10], args[0])


def test_graft_dryrun_multichip():
    import __graft_entry__ as ge

    ge.dryrun_multichip(8)


def test_new_coder_resolves_to_mesh():
    """The PRODUCTION coder path (new_coder, used by Store and the EC
    RPC handlers) must ride the mesh whenever >1 device exists — VERDICT
    round 2 #2: multi-chip as a capability of the product, not a demo."""
    from seaweedfs_tpu.models.coder import AutoMeshCoder, new_coder

    c = new_coder(10, 4)
    assert isinstance(c, AutoMeshCoder)
    assert isinstance(c._resolve(), ShardedCoder)
    rng = np.random.default_rng(3)
    data = rng.integers(0, 256, size=(10, 3000), dtype=np.uint8)
    ref = RSCodecCPU(10, 4).encode_parity(data)
    assert np.array_equal(np.asarray(c.encode_parity(data)), ref)


def test_generate_ec_files_mesh_bit_identical(tmp_path):
    """generate_ec_files + rebuild_ec_files through the default production
    coder (mesh-sharded on this 8-device suite) are byte-identical to the
    CPU oracle's shard files — odd payload size, different drop set than
    the dryrun's."""
    import __graft_entry__ as ge

    from seaweedfs_tpu.models.coder import new_coder

    ge.ec_file_pipeline_oracle(str(tmp_path), new_coder(10, 4),
                               batch_size=2000, drop=(0, 6, 13),
                               payload_len=351_003, seed=9)


def test_mesh_kernels_bit_identical():
    """xor vs bits per-device formulations agree byte-for-byte on the mesh."""
    import numpy as np

    from seaweedfs_tpu.parallel.mesh import ShardedCoder, make_mesh

    mesh = make_mesh()
    rng = np.random.default_rng(21)
    data = rng.integers(0, 256, size=(10, 4096), dtype=np.uint8)
    out = {}
    for kernel in ("xor", "bits"):
        coder = ShardedCoder(10, 4, mesh=mesh, kernel=kernel)
        shards = np.asarray(coder.encode(data))
        present = {i: shards[i] for i in range(14) if i not in (2, 7, 13)}
        rebuilt = coder.reconstruct(present)
        out[kernel] = (shards, {i: np.asarray(v) for i, v in rebuilt.items()})
        assert int(np.asarray(coder.parity_checksum(shards))) == 0
    np.testing.assert_array_equal(out["xor"][0], out["bits"][0])
    for i in (2, 7, 13):
        np.testing.assert_array_equal(out["xor"][1][i], out["bits"][1][i])
