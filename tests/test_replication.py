"""Replication, remote storage, and notification tests (SURVEY.md §2.6:
weed/replication, weed/remote_storage, weed/notification)."""

import os
import socket
import time

import pytest
import requests

from seaweedfs_tpu.notification import MemoryQueue, QUEUES, load_configuration
from seaweedfs_tpu.pb import filer_pb2, rpc
from seaweedfs_tpu.remote_storage import (
    LocalRemoteStorage,
    RemoteConf,
    RemoteGateway,
)
from seaweedfs_tpu.replication import (
    FilerSink,
    FilerSource,
    FilerSyncLoop,
    LocalSink,
    Replicator,
)
from seaweedfs_tpu.server.filer import FilerServer
from seaweedfs_tpu.server.master import MasterServer
from seaweedfs_tpu.server.volume import VolumeServer


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def _mk_cluster(tmp, tag):
    mport = _free_port()
    master = MasterServer(ip="localhost", port=mport,
                          volume_size_limit_mb=64)
    master.start(vacuum_interval=3600)
    vsrv = VolumeServer(directories=[str(tmp / f"vol-{tag}")],
                        master=f"localhost:{mport}", ip="localhost",
                        port=_free_port(), pulse_seconds=1)
    vsrv.start()
    fsrv = FilerServer(ip="localhost", port=_free_port(),
                       master=f"localhost:{mport}",
                       store_dir=str(tmp / f"filer-{tag}"),
                       chunk_size=64 * 1024)
    fsrv.start()
    deadline = time.time() + 10
    while time.time() < deadline and not master.topo.nodes:
        time.sleep(0.05)
    return master, vsrv, fsrv


@pytest.fixture(scope="module")
def two_clusters(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("repl")
    a = _mk_cluster(tmp, "a")
    b = _mk_cluster(tmp, "b")
    yield a, b
    for cluster in (a, b):
        for srv in reversed(cluster):
            srv.stop()
    rpc.reset_channels()


# -- notification ----------------------------------------------------------

def test_notification_registry_and_config():
    q = load_configuration({"notification": {"memory": {"enabled": True}}})
    assert isinstance(q, MemoryQueue)
    assert load_configuration({"notification": {}}) is None
    with pytest.raises(RuntimeError):
        QUEUES["gocdk_pub_sub"].initialize({})


def test_memory_queue_roundtrip():
    q = MemoryQueue()
    ev = filer_pb2.EventNotification()
    ev.new_entry.name = "x"
    q.send_message("/d/x", ev)
    drained = q.drain()
    assert len(drained) == 1 and drained[0][0] == "/d/x"
    assert drained[0][1].new_entry.name == "x"
    assert q.drain() == []


# -- local sink / replicator ----------------------------------------------

def test_replicator_to_local_sink(two_clusters, tmp_path):
    (_, _, fa), _ = two_clusters
    base = f"http://{fa.address}"
    requests.put(f"{base}/src/hello.txt", data=b"repl-payload", timeout=30)
    sink_dir = tmp_path / "mirror"
    repl = Replicator(FilerSource(fa.address), LocalSink(str(sink_dir)),
                      source_prefix="/src")
    stub = rpc.filer_stub(rpc.grpc_address(fa.address))
    import grpc

    n = 0
    try:
        for resp in stub.SubscribeMetadata(
                filer_pb2.SubscribeMetadataRequest(
                    client_name="t", path_prefix="/src", since_ns=0),
                timeout=2):
            if repl.replicate(resp):
                n += 1
    except grpc.RpcError as e:
        assert e.code() == grpc.StatusCode.DEADLINE_EXCEEDED
    assert n >= 1
    assert (sink_dir / "hello.txt").read_bytes() == b"repl-payload"
    # delete propagates (resume from a cursor, as a real consumer would)
    t1 = time.time_ns()
    requests.delete(f"{base}/src/hello.txt", timeout=30)
    try:
        for resp in stub.SubscribeMetadata(
                filer_pb2.SubscribeMetadataRequest(
                    client_name="t2", path_prefix="/src", since_ns=t1),
                timeout=2):
            repl.replicate(resp)
    except grpc.RpcError:
        pass
    assert not (sink_dir / "hello.txt").exists()


def test_s3_sink_e2e_via_own_gateway(two_clusters):
    """VERDICT r3 item 5: filer A events -> S3Sink -> this framework's
    OWN S3 gateway fronting filer B; byte + metadata equality, deletes
    propagate. (Reference: replication/sink/s3sink/s3_sink.go.)"""
    import grpc

    from seaweedfs_tpu.replication.sink import S3Sink
    from seaweedfs_tpu.s3api.server import S3Server

    (_, _, fa), (_, _, fb) = two_clusters
    s3port = _free_port()
    s3 = S3Server(port=s3port, filer=fb.address)
    s3.start()
    try:
        gw = f"http://localhost:{s3port}"
        assert requests.put(f"{gw}/mirror-bkt",
                            timeout=10).status_code == 200
        base = f"http://{fa.address}"
        payload = os.urandom(100_000)
        requests.put(f"{base}/s3src/deep/obj.bin", data=payload,
                     headers={"Content-Type": "application/x-test"},
                     timeout=30)
        repl = Replicator(
            FilerSource(fa.address),
            S3Sink(gw, "mirror-bkt", directory="mirrored"),
            source_prefix="/s3src")
        stub = rpc.filer_stub(rpc.grpc_address(fa.address))
        n = 0
        try:
            for resp in stub.SubscribeMetadata(
                    filer_pb2.SubscribeMetadataRequest(
                        client_name="s3t", path_prefix="/s3src",
                        since_ns=0), timeout=2):
                if repl.replicate(resp):
                    n += 1
        except grpc.RpcError as e:
            assert e.code() == grpc.StatusCode.DEADLINE_EXCEEDED
        assert n >= 1
        # byte equality through the gateway
        g = requests.get(f"{gw}/mirror-bkt/mirrored/deep/obj.bin",
                         timeout=30)
        assert g.status_code == 200 and g.content == payload
        # metadata (mime) carried across both hops
        assert g.headers["Content-Type"] == "application/x-test"
        # and equality straight from filer B's store
        e = fb.filer.find_entry("/buckets/mirror-bkt/mirrored/deep/obj.bin")
        from seaweedfs_tpu.filer.filechunks import total_size
        assert total_size(e.chunks) == len(payload)
        assert e.attr.mime == "application/x-test"

        # deletes propagate (resume from a cursor like a real consumer)
        t1 = time.time_ns()
        requests.delete(f"{base}/s3src/deep/obj.bin", timeout=30)
        try:
            for resp in stub.SubscribeMetadata(
                    filer_pb2.SubscribeMetadataRequest(
                        client_name="s3t2", path_prefix="/s3src",
                        since_ns=t1), timeout=2):
                repl.replicate(resp)
        except grpc.RpcError:
            pass
        g = requests.get(f"{gw}/mirror-bkt/mirrored/deep/obj.bin",
                         timeout=30)
        assert g.status_code == 404
    finally:
        s3.stop()


# -- filer -> filer sync ---------------------------------------------------

def test_filer_sync_between_clusters(two_clusters):
    (_, _, fa), (_, _, fb) = two_clusters
    t0 = time.time_ns()
    base_a = f"http://{fa.address}"
    requests.put(f"{base_a}/docs/a.txt", data=b"alpha", timeout=30)
    requests.put(f"{base_a}/docs/b.txt", data=b"beta" * 1000, timeout=30)
    loop = FilerSyncLoop(fa.address, fb.address, source_path="/docs")
    loop.run_once(since_ns=t0)
    assert loop.replicated >= 2
    rb = requests.get(f"http://{fb.address}/docs/a.txt", timeout=30)
    assert rb.status_code == 200 and rb.content == b"alpha"
    rb = requests.get(f"http://{fb.address}/docs/b.txt", timeout=30)
    assert rb.content == b"beta" * 1000
    # cursor persisted: a second drain replays nothing
    before = loop.replicated
    loop.run_once()
    assert loop.replicated == before
    # loop-prevention marker: target events carry is_from_other_cluster?
    # (FilerSink writes via HTTP; marker applies on gRPC writes — deletes)
    requests.delete(f"{base_a}/docs/a.txt", timeout=30)
    loop.run_once()
    assert requests.get(f"http://{fb.address}/docs/a.txt",
                        timeout=30).status_code == 404


# -- remote storage --------------------------------------------------------

def test_remote_mount_sync_cache_uncache(two_clusters, tmp_path):
    (_, _, fa), _ = two_clusters
    remote_root = tmp_path / "cloud"
    store = LocalRemoteStorage(str(remote_root))
    store.write_file("/photos/x.jpg", b"jpegbytes" * 100)
    store.write_file("/photos/y.jpg", b"other")

    conf = RemoteConf(fa.address)
    conf.configure_storage("mycloud", {"type": "local",
                                       "root": str(remote_root)})
    conf.mount("/buckets/pix", "mycloud", "/")
    gw = RemoteGateway(fa.address)
    n = gw.sync_dir("/buckets/pix")
    assert n == 2
    # metadata mirrored, no data yet
    stub = rpc.filer_stub(rpc.grpc_address(fa.address))
    e = stub.LookupDirectoryEntry(filer_pb2.LookupDirectoryEntryRequest(
        directory="/buckets/pix/photos", name="x.jpg"), timeout=10).entry
    assert e.attributes.file_size == 900
    assert not e.chunks and not e.content
    # cache materializes bytes
    assert gw.cache("/buckets/pix/photos/x.jpg") == 900
    r = requests.get(f"http://{fa.address}/buckets/pix/photos/x.jpg",
                     timeout=30)
    assert r.content == b"jpegbytes" * 100
    # uncache drops chunks, keeps metadata
    gw.uncache("/buckets/pix/photos/x.jpg")
    e = stub.LookupDirectoryEntry(filer_pb2.LookupDirectoryEntryRequest(
        directory="/buckets/pix/photos", name="x.jpg"), timeout=10).entry
    assert not e.chunks
    assert e.attributes.file_size == 900
    conf.unmount("/buckets/pix")
    assert conf.load()["mounts"] == {}


def test_filer_sync_active_active_no_loop(two_clusters):
    (_, _, fa), (_, _, fb) = two_clusters
    t0 = time.time_ns()
    ab = FilerSyncLoop(fa.address, fb.address, source_path="/aa")
    ba = FilerSyncLoop(fb.address, fa.address, source_path="/aa")
    requests.put(f"http://{fa.address}/aa/ping.txt", data=b"ping",
                 timeout=30)
    ab.run_once(since_ns=t0)
    assert requests.get(f"http://{fb.address}/aa/ping.txt",
                        timeout=30).content == b"ping"
    # reverse drain must see the replicated write flagged from-other-cluster
    cursor = ba.run_once(since_ns=t0)
    assert ba.replicated == 0, "replication loop: event bounced back"
    # and a fresh forward drain replicates nothing new
    before = ab.replicated
    ab.run_once()
    assert ab.replicated == before


def test_remote_resync_preserves_cache(two_clusters, tmp_path):
    (_, _, fa), _ = two_clusters
    remote_root = tmp_path / "cloud2"
    store = LocalRemoteStorage(str(remote_root))
    store.write_file("/doc.txt", b"original-remote")
    conf = RemoteConf(fa.address)
    conf.configure_storage("c2", {"type": "local", "root": str(remote_root)})
    conf.mount("/buckets/c2", "c2", "/")
    gw = RemoteGateway(fa.address)
    assert gw.sync_dir("/buckets/c2") == 1
    gw.cache("/buckets/c2/doc.txt")
    # unchanged remote -> resync must keep the cached chunks
    assert gw.sync_dir("/buckets/c2") == 0
    r = requests.get(f"http://{fa.address}/buckets/c2/doc.txt", timeout=30)
    assert r.content == b"original-remote"


def test_fs_shell_commands(two_clusters):
    import io

    from seaweedfs_tpu.shell.env import CommandEnv
    from seaweedfs_tpu.shell.registry import run_command

    (ma, _, fa), _ = two_clusters
    env = CommandEnv(f"localhost:{ma.port}", filer=fa.address)
    base = f"http://{fa.address}"
    requests.put(f"{base}/fstest/sub/x.txt", data=b"xx", timeout=30)
    requests.put(f"{base}/fstest/y.txt", data=b"yyy", timeout=30)

    def run(line):
        out = io.StringIO()
        assert run_command(env, line, out) == 0, out.getvalue()
        return out.getvalue()

    assert "fstest" in run("fs.ls /")
    run("fs.cd /fstest")
    assert run("fs.pwd").strip() == "/fstest"
    assert set(run("fs.ls").splitlines()) == {"sub/", "y.txt"}
    assert "yyy" in run("fs.cat y.txt")
    du = run("fs.du /fstest")
    assert "2 files" in " ".join(du.split())
    run("fs.mkdir /fstest/newdir")
    assert "newdir/" in run("fs.ls /fstest")
    run("fs.mv /fstest/y.txt /fstest/sub")
    assert requests.get(f"{base}/fstest/sub/y.txt",
                        timeout=30).content == b"yyy"
    # meta save/load round-trip into a different subtree of cluster B
    import tempfile

    with tempfile.NamedTemporaryFile(suffix=".bin") as tf:
        out = run(f"fs.meta.save -o={tf.name} /fstest")
        assert "saved" in out
        (_, _, fb) = two_clusters[1]
        env_b = CommandEnv(f"localhost:{ma.port}", filer=fb.address)
        outb = io.StringIO()
        assert run_command(env_b, f"fs.meta.load {tf.name}", outb) == 0
        assert "loaded" in outb.getvalue()
    run("fs.rm -r /fstest")
    assert "fstest" not in run("fs.ls /")


def test_local_remote_storage_traverse(tmp_path):
    s = LocalRemoteStorage(str(tmp_path / "r"))
    s.write_file("/a/b.txt", b"1")
    s.write_file("/c.txt", b"22")
    got = {e.path: e.size for e in s.traverse()}
    assert got == {"/a/b.txt": 1, "/c.txt": 2}
    s.delete_file("/c.txt")
    assert [e.path for e in s.traverse()] == ["/a/b.txt"]
