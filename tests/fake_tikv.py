"""In-process fake TiKV: a pdpb.PD servicer (GetMembers/GetRegion/
GetStore) and per-"store" tikvpb.Tikv RawKV servicers over the same
kvproto wire the real cluster speaks. The keyspace is split into TWO
regions at a configurable boundary and served by two distinct gRPC
servers, so the client's PD routing loop (key->region->store->stub) and
cross-region scan/delete-range splitting are exercised for real: every
request's Context is validated against the region that actually owns
the key range — wrong region id/epoch or a key outside the region's
bounds returns a region_error exactly like a real region server.
"""

from __future__ import annotations

import threading

from seaweedfs_tpu.pb import rpc
from seaweedfs_tpu.pb import tikv_kvrpc_pb2 as K
from seaweedfs_tpu.pb import tikv_meta_pb2 as M
from seaweedfs_tpu.pb import tikv_pd_pb2 as P

CLUSTER_ID = 7_431_998


class _RegionServicer:
    """One region server ("store") owning [start_key, end_key)."""

    def __init__(self, region: M.Region, data: dict[bytes, bytes],
                 lock: threading.Lock):
        self.region = region
        self.data = data  # shared, range-partitioned by _owns
        self.lock = lock

    def _owns(self, key: bytes) -> bool:
        r = self.region
        if r.start_key and key < r.start_key:
            return False
        if r.end_key and key >= r.end_key:
            return False
        return True

    def _ctx_error(self, ctx: K.Context, *keys: bytes):
        r = self.region
        if ctx.region_id != r.id:
            return K.RegionError(
                message=f"region {ctx.region_id} not found on store")
        if (ctx.region_epoch.version != r.region_epoch.version
                or ctx.region_epoch.conf_ver != r.region_epoch.conf_ver):
            return K.RegionError(message="epoch_not_match")
        for k in keys:
            if k and not self._owns(k):
                return K.RegionError(message="key not in region")
        return None

    def RawGet(self, req: K.RawGetRequest, _):
        err = self._ctx_error(req.context, req.key)
        if err:
            return K.RawGetResponse(region_error=err)
        with self.lock:
            if req.key not in self.data:
                return K.RawGetResponse(not_found=True)
            return K.RawGetResponse(value=self.data[req.key])

    def RawPut(self, req: K.RawPutRequest, _):
        err = self._ctx_error(req.context, req.key)
        if err:
            return K.RawPutResponse(region_error=err)
        with self.lock:
            self.data[req.key] = req.value
        return K.RawPutResponse()

    def RawDelete(self, req: K.RawDeleteRequest, _):
        err = self._ctx_error(req.context, req.key)
        if err:
            return K.RawDeleteResponse(region_error=err)
        with self.lock:
            self.data.pop(req.key, None)
        return K.RawDeleteResponse()

    def _range_keys(self, start: bytes, end: bytes) -> list[bytes]:
        return sorted(k for k in self.data
                      if self._owns(k) and k >= start
                      and (not end or k < end))

    def RawScan(self, req: K.RawScanRequest, _):
        err = self._ctx_error(req.context, req.start_key)
        if err:
            return K.RawScanResponse(region_error=err)
        with self.lock:
            keys = self._range_keys(req.start_key, req.end_key)
            if req.limit:
                keys = keys[:req.limit]
            return K.RawScanResponse(kvs=[
                K.KvPair(key=k, value=self.data[k]) for k in keys])

    def RawDeleteRange(self, req: K.RawDeleteRangeRequest, _):
        # a real region server rejects ranges reaching past its bounds
        r = self.region
        if req.end_key and r.end_key and req.end_key > r.end_key:
            return K.RawDeleteRangeResponse(region_error=K.RegionError(
                message="range spills past region end"))
        err = self._ctx_error(req.context, req.start_key)
        if err:
            return K.RawDeleteRangeResponse(region_error=err)
        with self.lock:
            for k in self._range_keys(req.start_key, req.end_key):
                del self.data[k]
        return K.RawDeleteRangeResponse()


class _PDServicer:
    def __init__(self, regions: list[M.Region],
                 stores: dict[int, M.Store]):
        self.regions = regions
        self.stores = stores

    def _hdr(self):
        return P.ResponseHeader(cluster_id=CLUSTER_ID)

    def GetMembers(self, req: P.GetMembersRequest, _):
        m = P.Member(name="pd-0", member_id=1)
        return P.GetMembersResponse(header=self._hdr(), members=[m],
                                    leader=m)

    def GetRegion(self, req: P.GetRegionRequest, _):
        for r in self.regions:
            if ((not r.start_key or req.region_key >= r.start_key)
                    and (not r.end_key or req.region_key < r.end_key)):
                return P.GetRegionResponse(header=self._hdr(), region=r,
                                           leader=r.peers[0])
        return P.GetRegionResponse(header=self._hdr())

    def GetStore(self, req: P.GetStoreRequest, _):
        s = self.stores.get(req.store_id)
        if s is None:
            return P.GetStoreResponse(header=P.ResponseHeader(
                cluster_id=CLUSTER_ID,
                error=P.Error(message=f"store {req.store_id} not found")))
        return P.GetStoreResponse(header=self._hdr(), store=s)


class FakeTikvCluster:
    """PD + two region servers splitting the keyspace at `split_key`."""

    def __init__(self, split_key: bytes = b"\x80"):
        self.data: dict[bytes, bytes] = {}
        lock = threading.Lock()
        self._servers = []
        regions, stores = [], {}
        bounds = [(b"", split_key), (split_key, b"")]
        for i, (lo, hi) in enumerate(bounds, start=1):
            region = M.Region(
                id=i, start_key=lo, end_key=hi,
                region_epoch=M.RegionEpoch(conf_ver=1, version=5),
                peers=[M.Peer(id=100 + i, store_id=i)])
            srv = rpc.new_server(max_workers=8)
            rpc.add_servicer(srv, rpc.tikv_service(),
                             _RegionServicer(region, self.data, lock))
            port = srv.add_insecure_port("localhost:0")
            srv.start()
            self._servers.append(srv)
            regions.append(region)
            stores[i] = M.Store(id=i, address=f"localhost:{port}")
        pd = rpc.new_server(max_workers=8)
        rpc.add_servicer(pd, rpc.tikv_pd_service(),
                         _PDServicer(regions, stores))
        self.port = pd.add_insecure_port("localhost:0")
        pd.start()
        self._servers.append(pd)

    def stop(self) -> None:
        for s in self._servers:
            s.stop(grace=0.2)
