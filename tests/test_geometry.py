"""ISSUE 11 — pluggable code-geometry plane.

Covers: the registry; the LRC(10,2,2) construction (distance, minimal-read
plans, bit-identity across backends); the pinned RS(10,4) default (byte-
unchanged through the geometry plumbing); minimal-read rebuild + degraded
reads; geometry persistence round-trip with MIXED geometries on one
server; dispatch lane keys carrying the geometry id; the product-matrix
regenerating variant; and the registry-introspection consistency tests
(every registered geometry gets a CPU-oracle bit-identity test and a
repair-plan test, parametrized from the registry itself — registering a
new geometry auto-enrolls it).
"""

from __future__ import annotations

import hashlib
import itertools
import os

import numpy as np
import pytest

from seaweedfs_tpu.models import geometry as gm
from seaweedfs_tpu.models.coder import new_coder
from seaweedfs_tpu.ops import dispatch, gf256
from seaweedfs_tpu.storage.ec_files import (
    rebuild_ec_files,
    write_ec_files,
    write_sorted_file_from_idx,
)
from seaweedfs_tpu.storage.ec_locate import Geometry
from seaweedfs_tpu.storage.ec_volume import (
    EcVolume,
    load_volume_info,
    save_volume_info,
)
from seaweedfs_tpu.storage.needle import Needle
from seaweedfs_tpu.storage.store import Store

TEST_GEO_RS = Geometry(large_block=10000, small_block=100)
TEST_GEO_LRC = Geometry(large_block=10000, small_block=100,
                        code="lrc_10_2_2")

LRC = gm.get("lrc_10_2_2")
RS = gm.get("rs_10_4")

# sha256 of the lrc_10_2_2 encode matrix — freezes the construction
# (local XOR rows + g1[i]=2^i / g2[i]=4^i): shard bytes on disk depend
# on it, so any change is a data-format break, not a refactor.
LRC_MATRIX_SHA256 = (
    "6e0c3b091906feff52d8dfcd390f70d6d2fe1b87f920ba65baf79c0375b2feb0")


def _shards_for(geom, data):
    return np.concatenate(
        [data, gf256.gf_matmul(geom.parity_matrix(), data)])


# -- registry ---------------------------------------------------------------


def test_registry_builtins_present():
    got = gm.names()
    assert "rs_10_4" in got and "lrc_10_2_2" in got
    assert any(n.startswith("pm_mbr_") for n in got)


def test_registry_unknown_name_lists_registered():
    with pytest.raises(ValueError) as ei:
        gm.get("raptor_9000")
    msg = str(ei.value)
    assert "raptor_9000" in msg and "lrc_10_2_2" in msg \
        and "rs_10_4" in msg


def test_rs_names_resolve_on_demand():
    g = gm.get("rs_6_3")
    assert (g.data_shards, g.parity_shards) == (6, 3) and g.is_rs
    # and the (k, m) consistency check bites
    with pytest.raises(ValueError):
        gm.resolve(10, 4, "rs_6_3")


def test_duplicate_registration_rejected():
    with pytest.raises(ValueError):
        gm.register(gm.CodeGeometry(
            "rs_10_4", 10, 4, gf256.parity_matrix(10, 4)))
    # re-registering the SAME object is a no-op
    gm.register(gm.get("rs_10_4"))


def test_geometry_dataclass_code_name_and_validation():
    assert TEST_GEO_RS.code_name == "rs_10_4"
    assert TEST_GEO_LRC.code_name == "lrc_10_2_2"
    assert TEST_GEO_LRC.code_geometry() is LRC
    bogus = Geometry(code="nope_1_2")
    with pytest.raises(ValueError):
        bogus.code_geometry()
    # shard-count mismatch between layout and code is refused
    with pytest.raises(ValueError):
        Geometry(data_shards=6, parity_shards=3,
                 code="lrc_10_2_2").code_geometry()


# -- the LRC construction ---------------------------------------------------


def test_lrc_matrix_frozen():
    got = hashlib.sha256(LRC.encode_matrix().tobytes()).hexdigest()
    assert got == LRC_MATRIX_SHA256, (
        "lrc_10_2_2 generator changed — that breaks every LRC volume "
        "on disk")


def test_lrc_distance_and_four_loss_coverage():
    """Brute force over every erasure pattern: all <=3-shard losses
    decode (distance 4 — same as RS(10,4) up to 3), and exactly
    861/1001 4-loss patterns do (the tail RS keeps is the price of
    halving single-shard repair)."""
    g = LRC.encode_matrix()
    for e in (1, 2, 3):
        for lost in itertools.combinations(range(14), e):
            surv = [i for i in range(14) if i not in lost]
            assert gm.gf_rank(g[surv]) == 10, f"pattern {lost} lost data"
    rec4 = sum(
        1 for lost in itertools.combinations(range(14), 4)
        if gm.gf_rank(g[[i for i in range(14) if i not in lost]]) == 10)
    assert rec4 == 861


def test_lrc_local_groups():
    assert LRC.local_groups == (((0, 1, 2, 3, 4), 10),
                                ((5, 6, 7, 8, 9), 11))
    assert LRC.group_of(3) == ((0, 1, 2, 3, 4), 10)
    assert LRC.group_of(11) == ((5, 6, 7, 8, 9), 11)
    assert LRC.group_of(13) is None


def test_lrc_minimal_read_plan_every_single_loss():
    """THE repair-bandwidth claim, pattern by pattern: a loss inside a
    local group reads its 5 group peers; a global parity reads the 10
    data shards. Each plan's matrix must reproduce the lost bytes."""
    rng = np.random.default_rng(3)
    data = rng.integers(0, 256, (10, 257), np.uint8)
    shards = _shards_for(LRC, data)
    total_reads = 0
    for lost in range(14):
        plan = LRC.repair_plan(
            (lost,), tuple(i for i in range(14) if i != lost))
        grp = LRC.group_of(lost)
        if grp is not None:
            data_ids, psid = grp
            expect = tuple(sorted((set(data_ids) | {psid}) - {lost}))
            assert plan.reads == expect, (lost, plan.reads)
            assert len(plan.reads) == 5
        else:  # global parity: needs the k data shards
            assert plan.reads == tuple(range(10)), (lost, plan.reads)
        rec = gf256.gf_matmul(plan.matrix, shards[list(plan.reads)])
        assert np.array_equal(rec[0], shards[lost]), lost
        total_reads += len(plan.reads)
    # fleet-average single-shard repair cost: 80/140 vs RS's 140/140
    assert total_reads == 12 * 5 + 2 * 10 == 80
    rs_reads = sum(len(RS.single_loss_reads(i)) for i in range(14))
    assert total_reads / rs_reads <= 0.60


def test_lrc_double_loss_cross_group_plans_stay_local():
    plan = LRC.repair_plan((0, 7), tuple(i for i in range(14)
                                         if i not in (0, 7)))
    # one loss per group: the union of two local plans, no globals
    assert set(plan.reads) == {1, 2, 3, 4, 10, 5, 6, 8, 9, 11}


def test_lrc_unsolvable_patterns_raise():
    # four losses inside one group exceed its local+global budget
    with pytest.raises(gm.UnsolvableError):
        LRC.repair_plan((0, 1, 2, 3), (4, 5, 6, 7, 8, 9, 11))


# -- RS stays bit-identical through the geometry plumbing -------------------


def test_rs_repair_matrix_equals_legacy_fused_matrix():
    from seaweedfs_tpu.ops.rs_jax import fused_reconstruct_stacked_matrix

    for lost in [(0,), (1, 12), (0, 5, 10, 13)]:
        pres = tuple(i for i in range(14) if i not in lost)
        missing, pm = fused_reconstruct_stacked_matrix(10, 4, pres, 14)
        assert missing == lost
        assert np.array_equal(RS.repair_matrix(pres, missing), pm)


def test_rs_single_loss_always_reads_k():
    for lost in range(14):
        assert len(RS.single_loss_reads(lost)) == 10


def test_rs_golden_shards_unchanged_through_geometry_coder():
    """The pinned RS(10,4) fixture hashes from test_golden_identity must
    hold when the coder is built THROUGH the registry — the default
    path is byte-unchanged."""
    from tests.test_golden_identity import GOLDEN_SHARD_SHA256, _fixture

    data = _fixture()
    coder = new_coder(10, 4, backend="cpu", geometry=RS)
    parity = np.asarray(coder.encode_parity(data), np.uint8)
    shards = np.concatenate([data, parity], axis=0)
    got = [hashlib.sha256(s.tobytes()).hexdigest() for s in shards]
    assert got == GOLDEN_SHARD_SHA256


def test_rs_want_path_bytes_match_legacy_stacked():
    """want= (the minimal-read form) on an RS coder is a different code
    path (geometry solve) — bytes must equal the legacy fused path."""
    rng = np.random.default_rng(11)
    data = rng.integers(0, 256, (10, 512), np.uint8)
    cpu = new_coder(10, 4, backend="cpu")
    shards = np.concatenate(
        [data, np.asarray(cpu.encode_parity(data), np.uint8)])
    pres = tuple(i for i in range(14) if i not in (2, 13))
    stk = np.stack([shards[i] for i in pres])
    m_old, rows_old = cpu.reconstruct_stacked(pres, stk)
    m_new, rows_new = cpu.reconstruct_stacked(pres, stk, want=(2, 13))
    assert tuple(m_old) == tuple(m_new) == (2, 13)
    assert np.array_equal(np.asarray(rows_old), np.asarray(rows_new))


# -- registry-introspection consistency (CI satellite) ----------------------
#
# Parametrized FROM the registry: registering a new geometry makes these
# tests cover it automatically — the "every registered geometry has a
# CPU-oracle bit-identity test and a repair-plan test" guarantee.


@pytest.mark.parametrize("name", gm.names())
def test_every_registered_geometry_bit_identity(name):
    geom = gm.get(name)
    rng = np.random.default_rng(hash(name) % 2**32)
    if isinstance(geom, gm.ProductMatrixMBR):
        # non-systematic: structured product-matrix encode must equal
        # the plain generator-matrix realization (the CPU oracle)
        w = rng.integers(0, 256, (geom.message_symbols, 64), np.uint8)
        structured = geom.encode_stripe(w)
        via_matrix = gf256.gf_matmul(geom.generator_matrix(), w).reshape(
            geom.n_nodes, geom.sub_symbols, -1)
        assert np.array_equal(structured, via_matrix)
        return
    data = rng.integers(0, 256, (geom.data_shards, 512), np.uint8)
    cpu = new_coder(geom.data_shards, geom.parity_shards, backend="cpu",
                    geometry=geom)
    jx = new_coder(geom.data_shards, geom.parity_shards, backend="single",
                   geometry=geom)
    p_cpu = np.asarray(cpu.encode_parity(data), np.uint8)
    p_jax = np.asarray(jx.encode_parity(data), np.uint8)
    assert np.array_equal(p_cpu, p_jax), f"{name}: cpu != jax parity"
    assert np.array_equal(
        p_cpu, gf256.gf_matmul(geom.parity_matrix(), data))


@pytest.mark.parametrize("name", gm.names())
def test_every_registered_geometry_repair_plan(name):
    geom = gm.get(name)
    rng = np.random.default_rng(1 + hash(name) % 2**32)
    if isinstance(geom, gm.ProductMatrixMBR):
        w = rng.integers(0, 256, (geom.message_symbols, 48), np.uint8)
        nodes = geom.encode_stripe(w)
        failed = 1
        helpers = [i for i in range(geom.n_nodes) if i != failed][
            : geom.d_helpers]
        recv = {j: geom.helper_symbol(nodes[j], failed) for j in helpers}
        # repair bandwidth: d sub-symbols = ONE node's worth, < k nodes'
        moved = sum(len(v) for v in recv.values())
        assert moved == geom.sub_symbols * 48
        assert moved < geom.k_nodes * geom.sub_symbols * 48
        assert np.array_equal(geom.repair_node(failed, recv),
                              nodes[failed])
        # data survives: decode from any k nodes
        dec = geom.decode_stripe(
            {i: nodes[i] for i in range(geom.k_nodes)})
        assert np.array_equal(dec, w)
        return
    data = rng.integers(0, 256, (geom.data_shards, 128), np.uint8)
    shards = _shards_for(geom, data)
    for lost in range(geom.total_shards):
        plan = geom.repair_plan(
            (lost,),
            tuple(i for i in range(geom.total_shards) if i != lost))
        assert len(plan.reads) <= geom.data_shards
        rec = gf256.gf_matmul(plan.matrix, shards[list(plan.reads)])
        assert np.array_equal(rec[0], shards[lost]), (name, lost)


# -- LRC bit-identity across device backends --------------------------------


def test_lrc_identity_cpu_jax_stacked_and_want():
    rng = np.random.default_rng(23)
    data = rng.integers(0, 256, (10, 1024), np.uint8)
    cpu = new_coder(10, 4, backend="cpu", geometry=LRC)
    jx = new_coder(10, 4, backend="single", geometry=LRC)
    shards = np.concatenate(
        [data, np.asarray(cpu.encode_parity(data), np.uint8)])
    assert np.array_equal(
        np.asarray(jx.encode(data), np.uint8), shards)
    # stacked encode
    stack = rng.integers(0, 256, (3, 10, 200), np.uint8)
    assert np.array_equal(
        np.asarray(cpu.encode_parity_stacked(stack), np.uint8),
        np.asarray(jx.encode_parity_stacked(stack), np.uint8))
    # want-restricted local repair, both backends, sub-k survivor set
    plan = LRC.repair_plan((7,), tuple(i for i in range(14) if i != 7))
    stk = np.stack([shards[i] for i in plan.reads])
    for coder in (cpu, jx):
        mids, rows = coder.reconstruct_stacked(plan.reads, stk,
                                               want=(7,))
        assert tuple(mids) == (7,)
        assert np.array_equal(np.asarray(rows, np.uint8)[0], shards[7])
    # dict-surface reconstruct (complement form) agrees too
    rec = cpu.reconstruct({i: shards[i] for i in range(14)
                           if i not in (3, 12)})
    assert np.array_equal(np.asarray(rec[3], np.uint8), shards[3])
    assert np.array_equal(np.asarray(rec[12], np.uint8), shards[12])


def test_lrc_identity_mesh_backend():
    from seaweedfs_tpu.parallel import mesh

    if mesh.device_count() < 2:
        pytest.skip("single-device process: mesh equals RSCodecJax here")
    rng = np.random.default_rng(29)
    data = rng.integers(0, 256, (10, 4096), np.uint8)
    cpu = new_coder(10, 4, backend="cpu", geometry=LRC)
    msh = mesh.ShardedCoder(10, 4, geometry=LRC)
    assert np.array_equal(
        np.asarray(cpu.encode_parity(data), np.uint8),
        np.asarray(msh.encode_parity(data), np.uint8))
    shards = np.concatenate(
        [data, np.asarray(cpu.encode_parity(data), np.uint8)])
    pres = tuple(i for i in range(14) if i not in (1, 6))
    stk = np.stack([shards[i] for i in pres])
    m1, r1 = cpu.reconstruct_stacked(pres, stk)
    m2, r2 = msh.reconstruct_stacked(pres, stk)
    assert tuple(m1) == tuple(m2)
    assert np.array_equal(np.asarray(r1, np.uint8),
                          np.asarray(r2, np.uint8))


def test_stripe_level_geometry_rejected_by_coders():
    """A volume_capable=False geometry (non-systematic product-matrix)
    has NO parity block — a coder built over it would silently encode
    zero parity. Every constructor path must refuse."""
    pm = next(n for n in gm.names() if n.startswith("pm_mbr_"))
    g = gm.get(pm)
    with pytest.raises(ValueError, match="volume_capable"):
        new_coder(g.data_shards, g.parity_shards, backend="cpu",
                  geometry=pm)
    with pytest.raises(ValueError, match="volume_capable"):
        gm.as_geometry(g.data_shards, g.parity_shards, g)
    # and the systematic accessors themselves refuse
    with pytest.raises(TypeError):
        g.parity_matrix()
    with pytest.raises(TypeError):
        g.encode_matrix()


def test_vsharded_reconstruct_accepts_want():
    """The mesh-wide V-sharded reconstruct (the rebuild backlog fast
    path) must honor `want` — a rebuild's minimal-read form must not
    demote its batch to a single chip."""
    from seaweedfs_tpu.parallel import mesh

    if mesh.device_count() < 2:
        pytest.skip("single-device process")
    rng = np.random.default_rng(53)
    data = rng.integers(0, 256, (10, 256), np.uint8)
    shards = _shards_for(RS, data)
    msh = mesh.ShardedCoder(10, 4)
    pres = tuple(i for i in range(14) if i != 3)
    vstack = np.stack([np.stack([shards[i] for i in pres])] * 4)
    m1, r1 = msh.reconstruct_stacked_vsharded(pres, vstack, want=(3,))
    assert tuple(m1) == (3,)
    for v in range(4):
        assert np.array_equal(np.asarray(r1, np.uint8)[v, 0], shards[3])
    # lrc variant through the same path
    lshards = _shards_for(LRC, data)
    lmsh = mesh.ShardedCoder(10, 4, geometry=LRC)
    plan = LRC.repair_plan((2,), tuple(i for i in range(14) if i != 2))
    lstack = np.stack([np.stack([lshards[i] for i in plan.reads])] * 3)
    m2, r2 = lmsh.reconstruct_stacked_vsharded(plan.reads, lstack,
                                               want=(2,))
    assert tuple(m2) == (2,)
    for v in range(3):
        assert np.array_equal(np.asarray(r2, np.uint8)[v, 0],
                              lshards[2])


def test_lrc_identity_native_backend():
    from seaweedfs_tpu.ops import rs_native

    if not rs_native.available():
        pytest.skip("native toolchain unavailable")
    rng = np.random.default_rng(31)
    data = rng.integers(0, 256, (10, 2048), np.uint8)
    cpu = new_coder(10, 4, backend="cpu", geometry=LRC)
    nat = new_coder(10, 4, backend="native", geometry=LRC)
    assert np.array_equal(np.asarray(cpu.encode_parity(data)),
                          np.asarray(nat.encode_parity(data)))


# -- dispatch lane keys carry the geometry id (satellite 1) -----------------


def test_dispatch_lanes_keyed_by_geometry():
    """Two coders with IDENTICAL (k, m) but different generator matrices
    must never share a stacked dispatch: the store hands out distinct
    coders (each with its own scheduler), and even within one scheduler
    every lane key carries the geometry id."""
    rng = np.random.default_rng(41)
    data = rng.integers(0, 256, (10, 64), np.uint8)
    lrc_coder = new_coder(10, 4, backend="cpu", geometry=LRC)
    rs_coder = new_coder(10, 4, backend="cpu")
    s_lrc = dispatch.EcDispatchScheduler(lrc_coder, window=60.0)
    s_rs = dispatch.EcDispatchScheduler(rs_coder, window=60.0)
    try:
        assert s_lrc.geom_id == "lrc_10_2_2"
        assert s_rs.geom_id == "rs_10_4"
        f1 = s_lrc.encode_parity(data)
        with s_lrc._cv:
            keys = list(s_lrc._lanes)
        assert keys and all("lrc_10_2_2" in k for k in keys), keys
        pres = tuple(range(10))
        f2 = s_lrc.reconstruct_stacked(pres, data, want=(10,))
        with s_lrc._cv:
            rec_keys = [k for k in s_lrc._lanes if k[0] == "rec"]
        assert rec_keys == [("rec", "lrc_10_2_2", pres, False, (10,))]
        # results still correct after demand flush
        parity = np.asarray(f1.result(), np.uint8)
        assert np.array_equal(
            parity, gf256.gf_matmul(LRC.parity_matrix(), data))
        mids, rows = f2.result()
        assert tuple(mids) == (10,)
        assert np.array_equal(np.asarray(rows)[0], parity[0])
    finally:
        s_lrc.close()
        s_rs.close()


def test_store_coder_for_separates_geometries(tmp_path):
    st = Store([str(tmp_path)])
    c_rs = st.coder_for(TEST_GEO_RS)
    c_lrc = st.coder_for(TEST_GEO_LRC)
    assert c_rs is st.coder  # default geometry reuses the store coder
    assert c_lrc is not c_rs
    assert c_lrc.geometry_id == "lrc_10_2_2"
    assert st.coder_for(TEST_GEO_LRC) is c_lrc  # cached
    st.close()


# -- storage plane: files, rebuild, persistence -----------------------------


def _make_dat(path, nbytes, seed):
    rng = np.random.default_rng(seed)
    blob = rng.integers(0, 256, nbytes, np.uint8).tobytes()
    with open(path, "wb") as f:
        f.write(blob)
    return blob


def test_lrc_generate_and_minimal_rebuild(tmp_path):
    """write_ec_files under lrc_10_2_2, then single-shard rebuilds:
    a group shard reads 5 survivors, a global parity reads 10 — and the
    rebuilt files are byte-identical to the originals."""
    base = str(tmp_path / "v1")
    _make_dat(base + ".dat", 3210, 5)
    coder = new_coder(10, 4, backend="cpu", geometry=LRC)
    write_ec_files(base, coder, TEST_GEO_LRC)
    originals = {}
    for i in range(14):
        with open(TEST_GEO_LRC.shard_file_name(base, i), "rb") as f:
            originals[i] = f.read()
    for lost, expect_reads in ((2, 5), (11, 5), (13, 10)):
        os.remove(TEST_GEO_LRC.shard_file_name(base, lost))
        stats: dict = {}
        rebuilt = rebuild_ec_files(base, coder, TEST_GEO_LRC,
                                   stats=stats)
        assert rebuilt == [lost]
        assert stats["survivor_shards"] == expect_reads
        assert stats["geometry"] == "lrc_10_2_2"
        assert stats["survivor_bytes_read"] == \
            expect_reads * len(originals[lost])
        with open(TEST_GEO_LRC.shard_file_name(base, lost), "rb") as f:
            assert f.read() == originals[lost], f"shard {lost} changed"


def test_rs_rebuild_reads_exactly_k_not_all_survivors(tmp_path):
    """Even RS gains from the plan: the rebuild used to OPEN/READ every
    survivor (up to 13) while the decode used only the first k — now it
    reads exactly its decode set."""
    base = str(tmp_path / "v2")
    _make_dat(base + ".dat", 2048, 6)
    coder = new_coder(10, 4, backend="cpu")
    write_ec_files(base, coder, TEST_GEO_RS)
    with open(TEST_GEO_RS.shard_file_name(base, 0), "rb") as f:
        original = f.read()
    os.remove(TEST_GEO_RS.shard_file_name(base, 0))
    stats: dict = {}
    assert rebuild_ec_files(base, coder, TEST_GEO_RS,
                            stats=stats) == [0]
    assert stats["survivor_shards"] == 10
    with open(TEST_GEO_RS.shard_file_name(base, 0), "rb") as f:
        assert f.read() == original


def test_rebuild_want_limits_targets(tmp_path):
    """`want` rebuilds only the asked-for shards — the ec.rebuild flow
    where locally-absent shards exist on peers and need no rebuild."""
    base = str(tmp_path / "v3")
    _make_dat(base + ".dat", 1500, 7)
    coder = new_coder(10, 4, backend="cpu", geometry=LRC)
    write_ec_files(base, coder, TEST_GEO_LRC)
    os.remove(TEST_GEO_LRC.shard_file_name(base, 1))
    os.remove(TEST_GEO_LRC.shard_file_name(base, 8))
    rebuilt = rebuild_ec_files(base, coder, TEST_GEO_LRC, want=[8])
    assert rebuilt == [8]
    assert not os.path.exists(TEST_GEO_LRC.shard_file_name(base, 1))


def test_mixed_geometry_persistence_roundtrip_one_server(tmp_path):
    """Acceptance path: encode (rs + lrc on ONE store) -> unmount ->
    remount -> degraded read -> rebuild. The .vif names the geometry,
    the mount reads it back, and every consumer picks the right coder."""
    st = Store([str(tmp_path)])
    blobs: dict[int, dict[int, bytes]] = {}
    for vid, geo in ((1, TEST_GEO_RS), (2, TEST_GEO_LRC)):
        v = st.add_volume(vid)
        rng = np.random.default_rng(vid)
        blobs[vid] = {}
        for i in range(1, 15):
            data = rng.integers(
                0, 256, int(rng.integers(100, 900)), np.uint8).tobytes()
            v.write_needle(Needle.create(i, 0xABC, data))
            blobs[vid][i] = data
        base = v.file_name()
        with v._lock:
            v._sync_buffers()
        write_ec_files(base, st.coder_for(geo), geo)
        write_sorted_file_from_idx(base)
        save_volume_info(base, {
            "version": v.version, "dataShards": geo.data_shards,
            "parityShards": geo.parity_shards,
            "largeBlock": geo.large_block,
            "smallBlock": geo.small_block, "geometry": geo.code_name})
        st.unmount_volume(vid)
        st.mount_ec_shards(vid, "", list(range(geo.total_shards)))
    # geometry survives the mount
    assert st.find_ec_volume(1).geo.code_name == "rs_10_4"
    ev2 = st.find_ec_volume(2)
    assert ev2.geo.code_name == "lrc_10_2_2"
    assert ev2.coder.geometry_id == "lrc_10_2_2"
    # remount cycle (a restart): scan-driven load keeps the geometry
    st.unmount_ec_shards(2)
    st.mount_ec_shards(2, "", list(range(14)))
    ev2 = st.find_ec_volume(2)
    assert ev2.geo.code_name == "lrc_10_2_2"
    # degraded read: drop shard 2's mmap from the runtime (group loss)
    ev2.shard_files = {i: f for i, f in ev2.shard_files.items()
                       if i != 2}
    from seaweedfs_tpu.utils.stats import EC_REPAIR_BYTES

    before = EC_REPAIR_BYTES.value(geometry="lrc_10_2_2",
                                   kind="degraded_read")
    for i, data in blobs[2].items():
        n = Needle.from_bytes(ev2.read_needle_blob(i), ev2.version)
        assert n.data == data
    assert EC_REPAIR_BYTES.value(geometry="lrc_10_2_2",
                                 kind="degraded_read") > before
    # rs volume still reads (its own coder, its own lanes)
    ev1 = st.find_ec_volume(1)
    ev1.shard_files = {i: f for i, f in ev1.shard_files.items()
                      if i != 0}
    for i, data in blobs[1].items():
        n = Needle.from_bytes(ev1.read_needle_blob(i), ev1.version)
        assert n.data == data
    # rebuild the lost lrc shard from disk survivors and re-read
    base2 = (str(tmp_path) + "/2")
    os.remove(TEST_GEO_LRC.shard_file_name(base2, 2))
    stats: dict = {}
    assert rebuild_ec_files(base2, st.coder_for(TEST_GEO_LRC),
                            TEST_GEO_LRC, stats=stats) == [2]
    assert stats["survivor_shards"] == 5
    st.mount_ec_shards(2, "", list(range(14)))
    ev2 = st.find_ec_volume(2)
    assert 2 in ev2.shard_files
    for i, data in blobs[2].items():
        n = Needle.from_bytes(ev2.read_needle_blob(i), ev2.version)
        assert n.data == data
    st.close()


def test_unregistered_geometry_refused_at_mount(tmp_path):
    base = str(tmp_path / "v9")
    _make_dat(base + ".dat", 1000, 9)
    coder = new_coder(10, 4, backend="cpu")
    write_ec_files(base, coder, TEST_GEO_RS)
    # a needle map is required for EcVolume; fake a minimal one
    with open(base + ".idx", "wb") as f:
        f.write(b"")
    write_sorted_file_from_idx(base)
    save_volume_info(base, {"version": 3, "dataShards": 10,
                            "parityShards": 4,
                            "largeBlock": TEST_GEO_RS.large_block,
                            "smallBlock": TEST_GEO_RS.small_block,
                            "geometry": "mystery_code_1"})
    with pytest.raises(ValueError) as ei:
        EcVolume(base, coder)
    assert "mystery_code_1" in str(ei.value)
    # the vif itself still parses (the error is the registry's)
    assert load_volume_info(base)["geometry"] == "mystery_code_1"


# -- scrub: syndrome verify covers local AND global parity rows -------------


def test_scrub_syndrome_checks_local_and_global_parities(tmp_path):
    """Corrupt a LOCAL parity shard (10) and then a GLOBAL one (13) of
    an lrc volume: the syndrome sweep must flag and repair both — the
    re-encode multiplies the full generator, so every parity row is
    checked."""
    from seaweedfs_tpu.scrub.scrubber import Scrubber

    st = Store([str(tmp_path)])
    v = st.add_volume(7)
    rng = np.random.default_rng(77)
    blobs = {}
    for i in range(1, 20):
        data = rng.integers(0, 256,
                            int(rng.integers(100, 900)), np.uint8).tobytes()
        v.write_needle(Needle.create(i, 0xABC, data))
        blobs[i] = data
    base = v.file_name()
    with v._lock:
        v._sync_buffers()
    write_ec_files(base, st.coder_for(TEST_GEO_LRC), TEST_GEO_LRC)
    write_sorted_file_from_idx(base)
    save_volume_info(base, {
        "version": v.version, "dataShards": 10, "parityShards": 4,
        "largeBlock": TEST_GEO_LRC.large_block,
        "smallBlock": TEST_GEO_LRC.small_block,
        "geometry": "lrc_10_2_2"})
    st.unmount_volume(7)
    st.mount_ec_shards(7, "", list(range(14)))
    sc = Scrubber(st, None, interval_s=0, max_mbps=0)
    for bad in (10, 13):
        with open(TEST_GEO_LRC.shard_file_name(base, bad), "r+b") as f:
            f.seek(17)
            b = f.read(1)
            f.seek(-1, 1)
            f.write(bytes([b[0] ^ 0x3C]))
        report = sc.run_once(vid=7, full=True)
        culprits = [(f.shard_id, f.state) for f in report.findings
                    if f.kind == "ec_parity"]
        assert (bad, "repaired") in culprits, (bad, report.findings)
    # converged: clean sweep, correct reads
    r2 = sc.run_once(vid=7, full=True)
    assert not [f for f in r2.findings if f.kind == "ec_parity"]
    ev = st.find_ec_volume(7)
    for i, data in blobs.items():
        assert Needle.from_bytes(ev.read_needle_blob(i),
                                 ev.version).data == data
    st.close()
