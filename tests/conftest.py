import os

# Tests run on a virtual 8-device CPU mesh; the real TPU is reserved for
# bench.py. The container's sitecustomize registers the remote "axon" TPU
# plugin at interpreter start (and pins JAX_PLATFORMS=axon), so plain env
# vars are too late / overridden — switch platforms through jax.config
# before any backend is instantiated.
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: multi-GB EC scale tests (deselect with -m 'not slow')"
    )
    config.addinivalue_line(
        "markers",
        "chaos: failpoint-driven fault-injection suite (tests/test_chaos.py);"
        " runs inside the tier-1 -m 'not slow' selection"
    )


import faulthandler  # noqa: E402
import pytest  # noqa: E402

# Per-test watchdog: if any single test wedges for 5 minutes (the slowest
# legitimate test is ~70s), dump every thread's stack and kill the run —
# a diagnosable failure beats an infinitely hung CI/driver session.
_WATCHDOG_SECONDS = 300


@pytest.fixture(autouse=True)
def _hang_watchdog(request):
    # multi-GB "slow" tests get a far wider budget on loaded machines
    budget = 900 if request.node.get_closest_marker("slow") \
        else _WATCHDOG_SECONDS
    faulthandler.dump_traceback_later(budget, exit=True)
    yield
    faulthandler.cancel_dump_traceback_later()
