import os

# Lock-order witness (ISSUE 15): armed for the whole tier-1 run BEFORE
# any seaweedfs_tpu import — the utils/locks.py factories read the gate
# at construction time, so this line is what turns every chaos/dispatch/
# group-commit/pool scenario into a deadlock detector. Production keeps
# the default (off ⇒ the factories return plain threading primitives).
os.environ.setdefault("SWFS_LOCK_WITNESS", "1")

# Tests run on a virtual 8-device CPU mesh; the real TPU is reserved for
# bench.py. The container's sitecustomize registers the remote "axon" TPU
# plugin at interpreter start (and pins JAX_PLATFORMS=axon), so plain env
# vars are too late / overridden — switch platforms through jax.config
# before any backend is instantiated.
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: multi-GB EC scale tests (deselect with -m 'not slow')"
    )
    config.addinivalue_line(
        "markers",
        "chaos: failpoint-driven fault-injection suite (tests/test_chaos.py);"
        " runs inside the tier-1 -m 'not slow' selection"
    )


import faulthandler  # noqa: E402
import pytest  # noqa: E402

# Per-test watchdog: if any single test wedges for 5 minutes (the slowest
# legitimate test is ~70s), dump every thread's stack and kill the run —
# a diagnosable failure beats an infinitely hung CI/driver session.
_WATCHDOG_SECONDS = 300


@pytest.fixture(autouse=True)
def _lock_witness_guard():
    """Fail the test that (first) observed a lock-order violation. The
    witness records instead of raising (a daemon thread's raise would
    be swallowed), so this guard is what turns a recorded inversion
    into a red run.

    Deliberately NO locks.reset() between tests: lock order is a
    program-wide invariant (FreeBSD witness accumulates for the system
    lifetime), so an A->B established by one test legitimately
    convicts a B->A in a later one — that cross-test pairing is most
    of the detector's power. The cost is attribution: the failing test
    may only be the OBSERVER of an inversion another test's surviving
    daemon thread completed; the violation detail (lock names, thread
    names, first-seen site) is what localizes it."""
    from seaweedfs_tpu.utils import locks

    if not locks.witness_enabled():
        yield
        return
    before = len(locks.violations())
    yield
    after = locks.violations()
    assert len(after) <= before, (
        "lock-order witness recorded violations during this test "
        "(cross-thread acquisition-order inversion or rank breach):\n"
        + "\n".join(repr(v) for v in after[before:]))


@pytest.fixture(autouse=True)
def _hang_watchdog(request):
    # multi-GB "slow" tests get a far wider budget on loaded machines
    budget = 900 if request.node.get_closest_marker("slow") \
        else _WATCHDOG_SECONDS
    faulthandler.dump_traceback_later(budget, exit=True)
    yield
    faulthandler.cancel_dump_traceback_later()


# -- duration audit (ISSUE 3 satellite): every test that takes longer than
# the threshold MUST carry @pytest.mark.slow, or the run fails. PR 1 shipped
# a 252s mesh test into tier-1 unmarked and silently ate a third of the
# tier-1 budget for a round; this makes that class of regression loud.
_SLOW_AUDIT_THRESHOLD = float(os.environ.get("SWFS_TEST_SLOW_THRESHOLD",
                                             "120"))
_overlong: list[tuple[str, float]] = []


def pytest_runtest_logreport(report):
    if report.when != "call" or report.duration <= _SLOW_AUDIT_THRESHOLD:
        return
    if "slow" in getattr(report, "keywords", {}):
        return
    _overlong.append((report.nodeid, report.duration))


def pytest_terminal_summary(terminalreporter):
    if not _overlong:
        return
    terminalreporter.section("slow-test audit FAILED")
    for nodeid, dur in _overlong:
        terminalreporter.write_line(
            f"  {nodeid} took {dur:.1f}s (> {_SLOW_AUDIT_THRESHOLD:.0f}s) "
            f"without @pytest.mark.slow")
    terminalreporter.write_line(
        "  mark these slow (or speed them up) — unmarked long tests eat "
        "the tier-1 budget for every future run")


def pytest_sessionfinish(session, exitstatus):
    # flip a green run red when the audit tripped; pytest returns
    # session.exitstatus AFTER this hook, so the mutation sticks
    if _overlong and exitstatus == 0:
        session.exitstatus = 1
