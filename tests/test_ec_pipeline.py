"""End-to-end EC pipeline tests — the port of the reference's oracle
(/root/reference/weed/storage/erasure_coding/ec_test.go): encode a real
volume with shrunken block sizes, then walk every live needle and assert the
bytes read back through LocateData + shard files equal the .dat bytes, plus
reconstruct every interval from a random k-of-n shard subset.

Runs against the reference's committed fixture volume (1.dat/1.idx) when
present, and always against a synthetic volume.
"""

import os
import shutil

import numpy as np
import pytest

from seaweedfs_tpu.models.coder import new_coder
from seaweedfs_tpu.storage import ec_files, idx, needle_map, types
from seaweedfs_tpu.storage.ec_locate import Geometry, locate_data
from seaweedfs_tpu.storage import ec_volume as ecv

# ec_test.go:16-19 shrunken geometry
TEST_GEO = Geometry(large_block=10000, small_block=100)
REF_FIXTURE = "/root/reference/weed/storage/erasure_coding/1"


def _make_synthetic_volume(base: str, n_needles=40, seed=0) -> None:
    """Write a .dat of concatenated fake needle records + matching .idx.
    EC operates below the needle codec, so records are opaque padded blobs."""
    rng = np.random.default_rng(seed)
    # 8-byte superblock stand-in: offset 0 is never a needle (a zero stored
    # offset means "deleted" to the needle-map replay, ec_encoder.go:298)
    dat = bytearray(b"\x03" + bytes(7))
    entries = []
    for i in range(1, n_needles + 1):
        size = int(rng.integers(1, 4000))
        total = types.actual_size(size)
        offset = len(dat)
        blob = rng.integers(0, 256, total).astype(np.uint8).tobytes()
        dat += blob
        entries.append((i, types.offset_to_stored(offset), size))
    with open(base + ".dat", "wb") as f:
        f.write(bytes(dat))
    ids = np.array([e[0] for e in entries], np.uint64)
    offs = np.array([e[1] for e in entries], np.uint32)
    sizes = np.array([e[2] for e in entries], np.int32)
    with open(base + ".idx", "wb") as f:
        f.write(idx.pack_index_arrays(ids, offs, sizes))


def _read_ec_interval(base, geo, dat_size, offset, size):
    """Read .dat extent [offset, offset+size) back through the shard files."""
    out = bytearray()
    for iv in locate_data(geo, dat_size, offset, size):
        shard_id, shard_off = iv.to_shard_id_and_offset(geo)
        with open(geo.shard_file_name(base, shard_id), "rb") as f:
            f.seek(shard_off)
            out += f.read(iv.size)
    return bytes(out)


def _reconstruct_interval_from_subset(base, geo, coder, shard_id, shard_off, size, rng):
    """readFromOtherEcFiles (ec_test.go:143-174): reconstruct one shard's
    interval from a random k-subset of the other shards."""
    chosen = []
    while len(chosen) < geo.data_shards:
        n = int(rng.integers(0, geo.total_shards))
        if n == shard_id or n in chosen:
            continue
        chosen.append(n)
    bufs = {}
    for i in chosen:
        with open(geo.shard_file_name(base, i), "rb") as f:
            f.seek(shard_off)
            chunk = f.read(size)
        bufs[i] = np.frombuffer(chunk, np.uint8)
    rec = coder.reconstruct_data(bufs) if shard_id < geo.data_shards else coder.reconstruct(bufs)
    return np.asarray(rec[shard_id]).tobytes()


def _validate_volume(base, geo, coder, check_subsets=True):
    """validateFiles (ec_test.go:44-72)."""
    db = needle_map.read_needle_map(base + ".idx")
    dat_size = os.path.getsize(base + ".dat")
    with open(base + ".dat", "rb") as dat:
        rng = np.random.default_rng(42)
        count = 0
        for nid, stored_off, size in db.sorted_entries():
            offset = types.stored_to_actual_offset(stored_off)
            dat.seek(offset)
            want = dat.read(size)
            got = _read_ec_interval(base, geo, dat_size, offset, size)
            assert got == want, f"needle {nid:x} mismatch via shard read"
            if check_subsets:
                for iv in locate_data(geo, dat_size, offset, size):
                    shard_id, shard_off = iv.to_shard_id_and_offset(geo)
                    rec = _reconstruct_interval_from_subset(
                        base, geo, coder, shard_id, shard_off, iv.size, rng
                    )
                    with open(geo.shard_file_name(base, shard_id), "rb") as f:
                        f.seek(shard_off)
                        assert rec == f.read(iv.size), (
                            f"reconstructed interval mismatch needle {nid:x}"
                        )
            count += 1
        assert count > 0


@pytest.fixture(params=["tpu", "cpu"])
def coder(request):
    return new_coder(10, 4, request.param)


def test_encode_validate_synthetic(tmp_path, coder):
    base = str(tmp_path / "7")
    _make_synthetic_volume(base)
    ec_files.generate_ec_files(base, coder, TEST_GEO, batch_size=50)
    ec_files.write_sorted_file_from_idx(base)
    _validate_volume(base, TEST_GEO, coder)


@pytest.mark.skipif(
    not os.path.exists(REF_FIXTURE + ".dat"), reason="reference fixture absent"
)
def test_encode_validate_reference_fixture(tmp_path, coder):
    """The reference's own committed 2.5MB fixture volume, bufferSize=50
    (TestEncodingDecoding, ec_test.go:21-42)."""
    base = str(tmp_path / "1")
    shutil.copy(REF_FIXTURE + ".dat", base + ".dat")
    shutil.copy(REF_FIXTURE + ".idx", base + ".idx")
    ec_files.generate_ec_files(base, coder, TEST_GEO, batch_size=50)
    ec_files.write_sorted_file_from_idx(base)
    _validate_volume(base, TEST_GEO, coder, check_subsets=False)


def test_batch_size_invariance(tmp_path):
    """Shard files must be bit-identical regardless of batch size — this is
    what licenses the TPU path's large slabs vs the reference's 256KB."""
    coder = new_coder(10, 4, "tpu")
    base1 = str(tmp_path / "a")
    base2 = str(tmp_path / "b")
    _make_synthetic_volume(base1, seed=3)
    shutil.copy(base1 + ".dat", base2 + ".dat")
    shutil.copy(base1 + ".idx", base2 + ".idx")
    ec_files.generate_ec_files(base1, coder, TEST_GEO, batch_size=50)
    ec_files.generate_ec_files(base2, coder, TEST_GEO, batch_size=10000)
    for i in range(14):
        with open(TEST_GEO.shard_file_name(base1, i), "rb") as f1, open(
            TEST_GEO.shard_file_name(base2, i), "rb"
        ) as f2:
            assert f1.read() == f2.read(), f"shard {i} differs across batch sizes"


def test_shard_sizes_match_row_schedule(tmp_path):
    coder = new_coder(10, 4, "cpu")
    base = str(tmp_path / "s")
    _make_synthetic_volume(base, seed=5)
    dat_size = os.path.getsize(base + ".dat")
    ec_files.generate_ec_files(base, coder, TEST_GEO, batch_size=50)
    want = TEST_GEO.shard_size(dat_size)
    for i in range(14):
        assert os.path.getsize(TEST_GEO.shard_file_name(base, i)) == want


def test_rebuild_missing_shards(tmp_path):
    """ec.rebuild path: delete shards, regenerate, byte-compare
    (BASELINE config #3 semantics)."""
    coder = new_coder(10, 4, "tpu")
    base = str(tmp_path / "r")
    _make_synthetic_volume(base, seed=7)
    ec_files.generate_ec_files(base, coder, TEST_GEO, batch_size=100)
    originals = {}
    for i in (0, 5, 13):
        p = TEST_GEO.shard_file_name(base, i)
        with open(p, "rb") as f:
            originals[i] = f.read()
        os.remove(p)
    rebuilt = ec_files.rebuild_ec_files(base, coder, TEST_GEO, batch_size=1 << 20)
    assert sorted(rebuilt) == [0, 5, 13]
    for i, want in originals.items():
        with open(TEST_GEO.shard_file_name(base, i), "rb") as f:
            assert f.read() == want, f"rebuilt shard {i} differs"


def test_rebuild_too_many_missing(tmp_path):
    coder = new_coder(10, 4, "cpu")
    base = str(tmp_path / "t")
    _make_synthetic_volume(base, seed=8)
    ec_files.generate_ec_files(base, coder, TEST_GEO, batch_size=100)
    for i in range(5):
        os.remove(TEST_GEO.shard_file_name(base, i))
    with pytest.raises(ValueError):
        ec_files.rebuild_ec_files(base, coder, TEST_GEO)


def test_decode_roundtrip(tmp_path):
    """encode -> decode back to .dat must reproduce the original bytes up to
    the ecx-derived size (WriteDatFile/FindDatFileSize, ec_decoder.go)."""
    coder = new_coder(10, 4, "tpu")
    base = str(tmp_path / "d")
    _make_synthetic_volume(base, seed=9)
    with open(base + ".dat", "rb") as f:
        original = f.read()
    ec_files.generate_ec_files(base, coder, TEST_GEO, batch_size=100)
    ec_files.write_sorted_file_from_idx(base)
    dat_size = ec_files.find_dat_file_size(base)
    assert dat_size == len(original)  # synthetic volume is dense
    os.remove(base + ".dat")
    ec_files.write_dat_file(base, dat_size, TEST_GEO)
    with open(base + ".dat", "rb") as f:
        assert f.read() == original


def test_deletion_journal_and_ecx_rebuild(tmp_path):
    coder = new_coder(10, 4, "cpu")
    base = str(tmp_path / "j")
    _make_synthetic_volume(base, seed=10, n_needles=20)
    ec_files.generate_ec_files(base, coder, TEST_GEO, batch_size=100)
    ec_files.write_sorted_file_from_idx(base)
    vol = ecv.EcVolume(base, coder, TEST_GEO)
    # needle 5 present, then deleted
    blob = vol.read_needle_blob(5)
    assert len(blob) > 0
    vol.delete_needle(5)
    with pytest.raises(ecv.NotFoundError):
        vol.read_needle_blob(5)
    # journal holds the id
    with open(base + ".ecj", "rb") as f:
        assert int.from_bytes(f.read(8), "big") == 5
    # idx reconstruction appends a tombstone entry
    ec_files.write_idx_file_from_ec_index(base)
    ids, offs, sizes = idx.read_index_file(base + ".idx")
    assert int(ids[-1]) == 5 and int(sizes[-1]) == types.TOMBSTONE_FILE_SIZE
    # replaying the journal removes it and keeps the tombstone in .ecx
    ecv.rebuild_ecx_file(base)
    assert not os.path.exists(base + ".ecj")
    vol2 = ecv.EcVolume(base, coder, TEST_GEO)
    with pytest.raises(ecv.NotFoundError):
        vol2.read_needle_blob(5)
    vol.close()
    vol2.close()


def test_degraded_read(tmp_path):
    """Reads still return correct bytes with 4 shards gone
    (store_ec.go:339 degraded path)."""
    coder = new_coder(10, 4, "tpu")
    base = str(tmp_path / "g")
    _make_synthetic_volume(base, seed=11)
    ec_files.generate_ec_files(base, coder, TEST_GEO, batch_size=100)
    ec_files.write_sorted_file_from_idx(base)
    vol = ecv.EcVolume(base, coder, TEST_GEO)
    want = {nid: vol.read_needle_blob(nid) for nid in (1, 7, 25)}
    vol.close()
    for i in (0, 3, 9, 12):
        os.remove(TEST_GEO.shard_file_name(base, i))
    vol = ecv.EcVolume(base, coder, TEST_GEO)
    for nid, blob in want.items():
        assert vol.read_needle_blob(nid) == blob, f"degraded read needle {nid}"
    vol.close()


def test_locate_data_reference_cases():
    """TestLocateData (ec_test.go:189-200) pinned cases."""
    geo = TEST_GEO
    intervals = locate_data(geo, 10 * 10000 + 1, 10 * 10000, 1)
    assert len(intervals) == 1
    iv = intervals[0]
    assert (iv.block_index, iv.inner_block_offset, iv.size, iv.is_large_block) == (
        0, 0, 1, False,
    )
    assert iv.large_block_rows_count == 1
    # spanning read across large->small boundary
    intervals = locate_data(
        geo, 10 * 10000 + 1, 10 * 10000 // 2 + 100, 10 * 10000 + 1 - 10 * 10000 // 2 - 100
    )
    assert sum(i.size for i in intervals) == 10 * 10000 + 1 - 10 * 10000 // 2 - 100
