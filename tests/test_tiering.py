"""Backend tiering + offline volume tools (SURVEY.md §2.1 backend row,
weed/storage/backend + command/backup|compact|fix|export)."""

import os
import socket
import time
from types import SimpleNamespace

import numpy as np
import pytest
import requests

from seaweedfs_tpu.command.tools import (
    run_backup,
    run_compact,
    run_export,
    run_fix,
)
from seaweedfs_tpu.pb import rpc, volume_server_pb2 as vs
from seaweedfs_tpu.server.master import MasterServer
from seaweedfs_tpu.server.volume import VolumeServer
from seaweedfs_tpu.storage.backend import (
    DiskFile,
    LocalTierBackend,
    MmapFile,
    RemoteDatFile,
    register_tier_backend,
)
from seaweedfs_tpu.storage.needle import Needle
from seaweedfs_tpu.storage.volume import Volume


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def _fill_volume(tmp_path, vid=1, count=20):
    v = Volume(str(tmp_path), "", vid)
    rng = np.random.default_rng(vid)
    payloads = {}
    for i in range(1, count + 1):
        data = rng.integers(0, 256, size=500 + i * 37,
                            dtype=np.uint8).tobytes()
        v.write_needle(Needle.create(i, 0x1234, data))
        payloads[i] = data
    return v, payloads


# -- backend primitives ----------------------------------------------------

def test_disk_and_mmap_files(tmp_path):
    p = str(tmp_path / "f.bin")
    d = DiskFile(p, create=True)
    assert d.append(b"hello") == 0
    d.write_at(5, b" world")
    d.flush()
    assert d.read_at(0, 11) == b"hello world"
    assert d.size() == 11
    m = MmapFile(p)
    assert m.read_at(6, 5) == b"world"
    m.close()
    d.close()


def test_local_tier_backend_roundtrip(tmp_path):
    b = LocalTierBackend(str(tmp_path / "tier"))
    src = tmp_path / "src.bin"
    src.write_bytes(b"x" * 1000)
    assert b.upload("1.dat", str(src)) == 1000
    assert b.read_range("1.dat", 10, 5) == b"xxxxx"
    dst = tmp_path / "dst.bin"
    assert b.download("1.dat", str(dst)) == 1000
    r = RemoteDatFile(b, "1.dat", 1000)
    assert r.read_at(990, 100) == b"x" * 10  # clamped at size
    b.delete("1.dat")


# -- volume tiering --------------------------------------------------------

def test_volume_tier_roundtrip(tmp_path):
    backend = register_tier_backend(
        LocalTierBackend(str(tmp_path / "cloud"), name="testtier"))
    os.makedirs(tmp_path / "vols", exist_ok=True)
    v, payloads = _fill_volume(tmp_path / "vols")
    size_before = v.data_size()
    moved = v.tier_to_remote(backend)
    assert moved == size_before
    assert v.is_tiered and v.read_only
    assert not os.path.exists(v.file_name() + ".dat")
    # reads now range-fetch from the backend
    for nid, data in payloads.items():
        assert v.read_needle(nid).data == data
    with pytest.raises(IOError):
        v.write_needle(Needle(id=999, cookie=1, data=b"nope"))
    v.close()
    # reload from disk: sidecar routes reads to the tier
    v2 = Volume(str(tmp_path / "vols"), "", 1)
    assert v2.is_tiered
    assert v2.read_needle(5).data == payloads[5]
    # bring it back local
    back = v2.tier_from_remote()
    assert back == size_before and not v2.is_tiered
    assert v2.read_needle(7).data == payloads[7]
    assert not v2.read_only
    v2.close()


def test_tiered_volume_served_over_cluster(tmp_path):
    register_tier_backend(
        LocalTierBackend(str(tmp_path / "cloud"), name="srvtier"))
    mport = _free_port()
    master = MasterServer(ip="localhost", port=mport, volume_size_limit_mb=64)
    master.start(vacuum_interval=3600)
    vsrv = VolumeServer(directories=[str(tmp_path / "v")],
                        master=f"localhost:{mport}", ip="localhost",
                        port=_free_port(), pulse_seconds=1)
    vsrv.start()
    deadline = time.time() + 10
    while time.time() < deadline and not master.topo.nodes:
        time.sleep(0.05)
    try:
        # write a file, tier the volume via gRPC, then read through HTTP
        r = requests.get(f"http://localhost:{mport}/dir/assign?count=1",
                         timeout=10).json()
        fid, url = r["fid"], r["url"]
        payload = b"tiered-needle-payload" * 100
        pr = requests.put(f"http://{url}/{fid}", data=payload, timeout=30)
        assert pr.status_code == 201
        vid = int(fid.split(",")[0])
        stub = rpc.volume_stub(rpc.grpc_address(url))
        got = list(stub.VolumeTierMoveDatToRemote(
            vs.VolumeTierMoveDatToRemoteRequest(
                volume_id=vid, destination_backend_name="srvtier"),
            timeout=60))
        assert got and got[0].processed > 0
        gr = requests.get(f"http://{url}/{fid}", timeout=30)
        assert gr.status_code == 200 and gr.content == payload
        # and back down
        got = list(stub.VolumeTierMoveDatFromRemote(
            vs.VolumeTierMoveDatFromRemoteRequest(volume_id=vid),
            timeout=60))
        assert got and got[0].processed > 0
        gr = requests.get(f"http://{url}/{fid}", timeout=30)
        assert gr.content == payload
    finally:
        vsrv.stop()
        master.stop()
        rpc.reset_channels()


# -- offline tools ---------------------------------------------------------

def test_fix_rebuilds_idx(tmp_path):
    v, payloads = _fill_volume(tmp_path, vid=3)
    v.delete_needle(5)
    v.close()
    os.remove(str(tmp_path / "3.idx"))
    run_fix(SimpleNamespace(dir=str(tmp_path), volumeId=3, collection=""))
    v2 = Volume(str(tmp_path), "", 3)
    assert v2.read_needle(4).data == payloads[4]
    from seaweedfs_tpu.storage.errors import DeletedError, NotFoundError

    with pytest.raises((DeletedError, NotFoundError)):
        v2.read_needle(5)
    v2.close()


def test_compact_and_export(tmp_path):
    v, payloads = _fill_volume(tmp_path, vid=4)
    for nid in range(1, 11):
        v.delete_needle(nid)
    v.close()
    run_compact(SimpleNamespace(dir=str(tmp_path), volumeId=4,
                                collection=""))
    v2 = Volume(str(tmp_path), "", 4)
    assert v2.read_needle(15).data == payloads[15]
    v2.close()
    out = tmp_path / "exported"
    run_export(SimpleNamespace(dir=str(tmp_path), volumeId=4,
                               collection="", output=str(out)))
    names = os.listdir(out)
    assert len(names) == 10  # 20 written - 10 deleted


def test_backup_full_and_incremental(tmp_path):
    mport = _free_port()
    master = MasterServer(ip="localhost", port=mport, volume_size_limit_mb=64)
    master.start(vacuum_interval=3600)
    vsrv = VolumeServer(directories=[str(tmp_path / "v")],
                        master=f"localhost:{mport}", ip="localhost",
                        port=_free_port(), pulse_seconds=1)
    vsrv.start()
    deadline = time.time() + 10
    while time.time() < deadline and not master.topo.nodes:
        time.sleep(0.05)
    try:
        r = requests.get(f"http://localhost:{mport}/dir/assign?count=1",
                         timeout=10).json()
        fid, url = r["fid"], r["url"]
        requests.put(f"http://{url}/{fid}", data=b"first-payload",
                     timeout=30)
        vid = int(fid.split(",")[0])
        bdir = str(tmp_path / "backup")
        opts = SimpleNamespace(master=f"localhost:{mport}", server=url,
                               volumeId=vid, dir=bdir)
        assert run_backup(opts) == 0
        assert os.path.exists(os.path.join(bdir, f"{vid}.dat"))
        # append more, backup again (incremental path)
        r2 = requests.get(
            f"http://localhost:{mport}/dir/assign?count=1", timeout=10
        ).json()
        if int(r2["fid"].split(",")[0]) == vid:
            requests.put(f"http://{r2['url']}/{r2['fid']}",
                         data=b"second-payload", timeout=30)
        assert run_backup(opts) == 0
        v = Volume(bdir, "", vid)
        key = int(fid.split(",")[1][:8].lstrip("0") or "0", 16)
        assert v.file_count() >= 1
        v.close()
    finally:
        vsrv.stop()
        master.stop()
        rpc.reset_channels()
