"""gRPC mutual TLS (security.toml [grpc.*], reference
weed/security/tls.go): a full master+volume+filer cluster where every
gRPC plane requires client certificates; plaintext and cert-less
clients are rejected; common-name allow-lists gate verified peers."""

import datetime
import socket
import time

import grpc
import pytest
import requests

pytest.importorskip("cryptography")  # cert generation needs the wheel

from seaweedfs_tpu.pb import master_pb2, rpc
from seaweedfs_tpu.server.filer import FilerServer
from seaweedfs_tpu.server.master import MasterServer
from seaweedfs_tpu.server.volume import VolumeServer


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def _make_cert(subject_cn, issuer_cert=None, issuer_key=None, *,
               is_ca=False):
    """-> (cert_pem, key_pem, cert, key). Self-signed when no issuer."""
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import rsa
    from cryptography.x509.oid import NameOID

    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, subject_cn)])
    now = datetime.datetime.now(datetime.timezone.utc)
    builder = (x509.CertificateBuilder()
               .subject_name(name)
               .issuer_name(issuer_cert.subject if issuer_cert else name)
               .public_key(key.public_key())
               .serial_number(x509.random_serial_number())
               .not_valid_before(now - datetime.timedelta(minutes=5))
               .not_valid_after(now + datetime.timedelta(days=1))
               .add_extension(x509.BasicConstraints(ca=is_ca,
                                                    path_length=None),
                              critical=True))
    if not is_ca:
        builder = builder.add_extension(
            x509.SubjectAlternativeName([
                x509.DNSName("localhost"),
                x509.IPAddress(__import__("ipaddress")
                               .ip_address("127.0.0.1")),
            ]), critical=False)
    cert = builder.sign(issuer_key or key, hashes.SHA256())
    return (cert.public_bytes(serialization.Encoding.PEM),
            key.private_bytes(
                serialization.Encoding.PEM,
                serialization.PrivateFormat.TraditionalOpenSSL,
                serialization.NoEncryption()),
            cert, key)


@pytest.fixture
def tls_pki(tmp_path):
    """CA + per-component certs + a security.toml pointing at them,
    activated by scoping the config search path to tmp_path."""
    ca_pem, ca_key_pem, ca_cert, ca_key = _make_cert("weed-ca", is_ca=True)
    files = {"ca.pem": ca_pem}
    for who in ("master", "volume", "filer", "client", "intruder"):
        cert_pem, key_pem, _, _ = _make_cert(
            f"weed-{who}", issuer_cert=ca_cert, issuer_key=ca_key)
        files[f"{who}.crt"] = cert_pem
        files[f"{who}.key"] = key_pem
    for fn, blob in files.items():
        (tmp_path / fn).write_bytes(blob)

    def toml(**section_extras: str) -> None:
        body = [f'[grpc]\nca = "{tmp_path}/ca.pem"']
        for c in ("master", "volume", "filer", "client"):
            sec = (f'[grpc.{c}]\ncert = "{tmp_path}/{c}.crt"\n'
                   f'key = "{tmp_path}/{c}.key"')
            if c in section_extras:
                sec += "\n" + section_extras[c]
            body.append(sec)
        (tmp_path / "security.toml").write_text("\n".join(body) + "\n")

    toml()
    yield tmp_path, toml


@pytest.fixture
def tls_paths(tls_pki, monkeypatch):
    tmp_path, toml = tls_pki
    from seaweedfs_tpu.utils import config

    monkeypatch.setattr(config, "SEARCH_PATHS", [str(tmp_path)])
    rpc.reset_channels()  # drop plaintext channels + cached client creds
    yield tmp_path, toml
    rpc.reset_channels()


def test_mtls_cluster_end_to_end(tls_paths, tmp_path):
    """Heartbeats, assignment, and the filer metadata plane all ride
    mutual TLS; plaintext and cert-less clients are refused."""
    tls_dir, _ = tls_paths
    mport = _free_port()
    master = MasterServer(ip="localhost", port=mport,
                          volume_size_limit_mb=64)
    master.start(vacuum_interval=3600)
    vsrv = VolumeServer(directories=[str(tmp_path / "tlsvol")],
                        master=f"localhost:{mport}", ip="localhost",
                        port=_free_port(), pulse_seconds=1)
    vsrv.start()
    fs = FilerServer(ip="localhost", port=_free_port(),
                     master=f"localhost:{mport}", store="memory")
    fs.start()
    try:
        # volume -> master heartbeat stream crossed the mTLS boundary
        deadline = time.time() + 15
        while time.time() < deadline and not master.topo.nodes:
            time.sleep(0.05)
        assert master.topo.nodes, "no heartbeat over mTLS"
        # full write/read path: filer->master assign + filer gRPC all TLS
        base = f"http://{fs.address}"
        r = requests.put(f"{base}/tls/hello.txt", data=b"mutual tls",
                         timeout=30)
        assert r.status_code in (200, 201)
        g = requests.get(f"{base}/tls/hello.txt", timeout=30)
        assert g.status_code == 200 and g.content == b"mutual tls"
        # the secured master gRPC port works for a proper mTLS client
        stub = rpc.master_stub(rpc.grpc_address(master.address))
        assert stub.Ping(master_pb2.PingRequest(),
                         timeout=10).start_time_ns > 0
        gaddr = f"localhost:{master.grpc_port}"
        # plaintext client: rejected at the transport
        plain = grpc.insecure_channel(gaddr)
        with pytest.raises(grpc.RpcError) as e1:
            rpc.Stub(plain, rpc.MASTER_SERVICE).Ping(
                master_pb2.PingRequest(), timeout=5)
        assert e1.value.code() == grpc.StatusCode.UNAVAILABLE
        plain.close()
        # TLS WITHOUT a client cert: handshake refused (mutual is
        # required, tls.go RequireClientCert)
        anon = grpc.secure_channel(gaddr, grpc.ssl_channel_credentials(
            root_certificates=(tls_dir / "ca.pem").read_bytes()))
        with pytest.raises(grpc.RpcError) as e2:
            rpc.Stub(anon, rpc.MASTER_SERVICE).Ping(
                master_pb2.PingRequest(), timeout=5)
        assert e2.value.code() == grpc.StatusCode.UNAVAILABLE
        anon.close()
    finally:
        fs.stop()
        vsrv.stop()
        master.stop()


def test_mtls_common_name_allowlist(tls_paths):
    """allowed_commonNames (tls.go:64 Authenticator): a verified peer
    whose CN is not allowed gets UNAUTHENTICATED, an allowed CN works."""
    tls_dir, toml = tls_paths
    toml(master='allowed_commonNames = "weed-client"')
    mport = _free_port()
    master = MasterServer(ip="localhost", port=mport,
                          volume_size_limit_mb=64)
    master.start(vacuum_interval=3600)
    try:
        # allowed CN (weed-client, via the cached [grpc.client] creds)
        stub = rpc.master_stub(rpc.grpc_address(master.address))
        assert stub.Ping(master_pb2.PingRequest(),
                         timeout=10).start_time_ns > 0
        # a cert the CA signed but whose CN is not in the list
        creds = grpc.ssl_channel_credentials(
            root_certificates=(tls_dir / "ca.pem").read_bytes(),
            private_key=(tls_dir / "intruder.key").read_bytes(),
            certificate_chain=(tls_dir / "intruder.crt").read_bytes())
        ch = grpc.secure_channel(f"localhost:{master.grpc_port}", creds)
        with pytest.raises(grpc.RpcError) as ei:
            rpc.Stub(ch, rpc.MASTER_SERVICE).Ping(
                master_pb2.PingRequest(), timeout=5)
        assert ei.value.code() == grpc.StatusCode.UNAUTHENTICATED
        ch.close()
    finally:
        master.stop()


def test_plaintext_stays_default(tmp_path, monkeypatch):
    """No security.toml -> everything stays plaintext (every cert field
    defaults to '' in the scaffold, like the reference)."""
    from seaweedfs_tpu.utils import config

    monkeypatch.setattr(config, "SEARCH_PATHS", [str(tmp_path / "empty")])
    rpc.reset_channels()
    mport = _free_port()
    master = MasterServer(ip="localhost", port=mport,
                          volume_size_limit_mb=64)
    master.start(vacuum_interval=3600)
    try:
        stub = rpc.master_stub(rpc.grpc_address(master.address))
        assert stub.Ping(master_pb2.PingRequest(),
                         timeout=10).start_time_ns > 0
    finally:
        master.stop()
        rpc.reset_channels()


def test_server_only_config_still_dials_secured(tls_pki, monkeypatch,
                                                tmp_path):
    """A reference-style server-only security.toml (component certs, NO
    [grpc.client]) must not strand outbound dials on plaintext: the
    channel cache falls back to the first configured component cert."""
    tls_dir, _ = tls_pki
    body = [f'[grpc]\nca = "{tls_dir}/ca.pem"']
    for c in ("master", "volume", "filer"):
        body.append(f'[grpc.{c}]\ncert = "{tls_dir}/{c}.crt"\n'
                    f'key = "{tls_dir}/{c}.key"')
    (tls_dir / "security.toml").write_text("\n".join(body) + "\n")
    from seaweedfs_tpu.utils import config

    monkeypatch.setattr(config, "SEARCH_PATHS", [str(tls_dir)])
    rpc.reset_channels()
    mport = _free_port()
    master = MasterServer(ip="localhost", port=mport,
                          volume_size_limit_mb=64)
    master.start(vacuum_interval=3600)
    vsrv = VolumeServer(directories=[str(tmp_path / "sovol")],
                        master=f"localhost:{mport}", ip="localhost",
                        port=_free_port(), pulse_seconds=1)
    vsrv.start()
    try:
        deadline = time.time() + 15
        while time.time() < deadline and not master.topo.nodes:
            time.sleep(0.05)
        assert master.topo.nodes, \
            "volume->master heartbeat failed without [grpc.client]"
    finally:
        vsrv.stop()
        master.stop()
        rpc.reset_channels()


def test_cn_allowlist_without_certs_does_not_brick_server(tmp_path,
                                                          monkeypatch):
    """allowed_commonNames with a broken cert path: server TLS fails to
    load, the port binds plaintext — and the authenticator must NOT
    activate (the reference couples creds+authenticator in
    LoadServerTLS); otherwise every RPC dies UNAUTHENTICATED."""
    (tmp_path / "security.toml").write_text(
        f'[grpc]\nca = "{tmp_path}/missing-ca.pem"\n'
        f'[grpc.master]\ncert = "{tmp_path}/missing.crt"\n'
        f'key = "{tmp_path}/missing.key"\n'
        'allowed_commonNames = "weed-client"\n')
    from seaweedfs_tpu.utils import config

    monkeypatch.setattr(config, "SEARCH_PATHS", [str(tmp_path)])
    rpc.reset_channels()
    mport = _free_port()
    master = MasterServer(ip="localhost", port=mport,
                          volume_size_limit_mb=64)
    master.start(vacuum_interval=3600)
    try:
        stub = rpc.master_stub(rpc.grpc_address(master.address))
        assert stub.Ping(master_pb2.PingRequest(),
                         timeout=10).start_time_ns > 0
    finally:
        master.stop()
        rpc.reset_channels()


def test_shell_and_s3_control_over_mtls(tls_paths, tmp_path):
    """The admin shell's gRPC commands and the S3 Configure control
    plane both work over mTLS; the S3 control port rejects plaintext
    when [grpc.s3] is configured (the reference's LoadServerTLS gate
    on s3api_server.go's grpc listener)."""
    import io

    tls_dir, _ = tls_paths
    # extend the config with an s3 section (same CA/keypair family)
    with open(tls_dir / "security.toml", "a") as fh:
        fh.write(f'[grpc.s3]\ncert = "{tls_dir}/client.crt"\n'
                 f'key = "{tls_dir}/client.key"\n')
    from seaweedfs_tpu.pb import s3_pb2
    from seaweedfs_tpu.s3api.server import S3Server
    from seaweedfs_tpu.shell.env import CommandEnv
    from seaweedfs_tpu.shell.registry import run_command

    mport = _free_port()
    master = MasterServer(ip="localhost", port=mport,
                          volume_size_limit_mb=64)
    master.start(vacuum_interval=3600)
    fs = FilerServer(ip="localhost", port=_free_port(),
                     master=f"localhost:{mport}", store="memory")
    fs.start()
    s3 = S3Server(port=_free_port(), filer=fs.address)
    s3.start()
    try:
        # shell gRPC (lock + cluster.raft.ps) rides the mTLS channel
        env = CommandEnv(master.address)
        out = io.StringIO()
        assert run_command(env, "lock", out) == 0
        assert run_command(env, "cluster.raft.ps", out) == 0
        assert master.address in out.getvalue()
        # s3 Configure over mTLS (a real identity json body)
        stub = rpc.Stub(rpc.cached_channel(
            f"localhost:{rpc.derived_grpc_port(s3.port)}"),
            rpc.S3_SERVICE)
        conf = (b'{"identities":[{"name":"tls-admin","credentials":'
                b'[{"accessKey":"ak","secretKey":"sk"}],'
                b'"actions":["Admin"]}]}')
        stub.Configure(s3_pb2.S3ConfigureRequest(
            s3_configuration_file_content=conf), timeout=10)
        assert any(i.name == "tls-admin"
                   for i in s3.iam.identities.values()), \
            "Configure did not apply"
        # plaintext client: refused at the transport
        plain = grpc.insecure_channel(
            f"localhost:{rpc.derived_grpc_port(s3.port)}")
        with pytest.raises(grpc.RpcError) as ei:
            rpc.Stub(plain, rpc.S3_SERVICE).Configure(
                s3_pb2.S3ConfigureRequest(), timeout=5)
        assert ei.value.code() == grpc.StatusCode.UNAVAILABLE
        plain.close()
    finally:
        s3.stop()
        fs.stop()
        master.stop()
