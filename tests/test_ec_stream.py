"""Streaming replica->EC conversion (ISSUE 6): pipelined archival encode.

Covers the tentpole end to end IN-PROCESS against a live 3-server
cluster — stream -> encode -> remote-write -> mount — plus the
satellites:

  * streamed-vs-local bit identity: golden `.ec00-.ec13` hashes through
    the streaming path (ragged tail + small/large block schedule
    boundaries), and the generate-then-copy path against the same golden
  * `crc32c_combine`-folded destination `.dig` digests equal to a
    full-file CRC re-read
  * `ec.stream.slab` failpoint (per-shard, per-slab-range matchable) +
    chaos: destination flap mid-stream resumes ONLY the missing range,
    final shards bit-identical, zero client-visible errors
  * the `_do_ec_encode` read-only rollback regression (generate failure
    must restore replica writability)
  * `SeaweedFS_ec_stream_*` metrics, the `/status` EcStream section and
    the VolumeEcShardsCopy fallback counters
"""

import hashlib
import io
import os
import socket
import time

import numpy as np
import pytest
import requests

from seaweedfs_tpu.operation import submit
from seaweedfs_tpu.pb import ec_stream_pb2 as es, rpc
from seaweedfs_tpu.pb import volume_server_pb2 as vs
from seaweedfs_tpu.scrub import digest as digest_mod
from seaweedfs_tpu.server.master import MasterServer
from seaweedfs_tpu.server.volume import VolumeServer
from seaweedfs_tpu.shell.env import CommandEnv
from seaweedfs_tpu.shell.registry import run_command
from seaweedfs_tpu.storage import ec_files
from seaweedfs_tpu.storage.crc import crc32c
from seaweedfs_tpu.storage.ec_locate import Geometry
from seaweedfs_tpu.storage.file_id import parse_file_id
from seaweedfs_tpu.utils import failpoint, stats

# small blocks so a few KB of needles cross the large/small row boundary
TEST_GEO = Geometry(large_block=10000, small_block=100)


def _free_port() -> int:
    """A free HTTP port whose +10000 gRPC sibling is also free."""
    for _ in range(50):
        with socket.socket() as s:
            s.bind(("", 0))
            port = s.getsockname()[1]
        if port + 10000 > 65535:
            continue
        with socket.socket() as s2:
            try:
                s2.bind(("", port + 10000))
            except OSError:
                continue
        return port
    raise RuntimeError("no free port pair found")


@pytest.fixture(autouse=True)
def _no_leaked_failpoints():
    failpoint.clear()
    yield
    failpoint.clear()


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    """master + 3 volume servers; the python engine (native off) so the
    test controls the volume files directly."""
    old_native = os.environ.get("SEAWEEDFS_TPU_NATIVE")
    os.environ["SEAWEEDFS_TPU_NATIVE"] = "0"
    # wire chunks aligned to TEST_GEO.large_block so shard streams span
    # multiple chunks (the resume/failpoint tests target chunk offsets)
    old_chunk = os.environ.get("SWFS_EC_STREAM_CHUNK")
    os.environ["SWFS_EC_STREAM_CHUNK"] = str(TEST_GEO.large_block)
    tmp = tmp_path_factory.mktemp("ecstream")
    mport = _free_port()
    master = MasterServer(ip="localhost", port=mport,
                          volume_size_limit_mb=64)
    master.start(vacuum_interval=3600)
    volumes = []
    for i in range(3):
        vsrv = VolumeServer(
            directories=[str(tmp / f"vol{i}")],
            master=f"localhost:{mport}", ip="localhost",
            port=_free_port(), pulse_seconds=1, ec_geometry=TEST_GEO,
            # every test grows a fresh collection (~7 volumes each);
            # leave headroom so later tests never hit "no free slot"
            max_volume_counts=[120])
        vsrv.start()
        volumes.append(vsrv)
    deadline = time.time() + 10
    while time.time() < deadline and len(master.topo.nodes) < 3:
        time.sleep(0.05)
    assert len(master.topo.nodes) == 3, "volume servers did not register"
    env = CommandEnv(master.address)
    out = io.StringIO()
    assert run_command(env, "lock", out) == 0
    yield master, volumes, env
    for v in volumes:
        v.stop()
    master.stop()
    rpc.reset_channels()
    if old_native is None:
        os.environ.pop("SEAWEEDFS_TPU_NATIVE", None)
    else:
        os.environ["SEAWEEDFS_TPU_NATIVE"] = old_native
    if old_chunk is None:
        os.environ.pop("SWFS_EC_STREAM_CHUNK", None)
    else:
        os.environ["SWFS_EC_STREAM_CHUNK"] = old_chunk


def _make_volume(master, volumes, collection, n_needles=30, seed=0,
                 min_payload=0):
    """Write needles into ONE volume -> (vid, {fid: payload}, source
    server): the first needle goes through the live assign path to grow
    the collection, the rest PUT directly into that volume so the whole
    payload stripes one .dat. Sizes span sub-block to multi-block so the
    stripe crosses small/large rows with a ragged tail."""
    rng = np.random.default_rng(seed)
    res = submit(master.address, b"seed-needle", filename="seed.bin",
                 collection=collection)
    assert "fid" in res, res
    fid = res["fid"]
    vid = parse_file_id(fid).volume_id
    src = next(v for v in volumes if v.store.has_volume(vid))
    blobs = {fid: b"seed-needle"}
    # the master's sequencer adopts the max key it observes in
    # heartbeats, so a FIXED direct-key base would be chased and
    # eventually collided with by later seed assigns — descend the base
    # per test instead (seeds are distinct per collection)
    key = (0x7F - seed) << 24
    total = 0
    while len(blobs) < n_needles or total < min_payload:
        size = int(rng.integers(40, 5000))
        data = rng.integers(0, 256, size=size, dtype=np.uint8).tobytes()
        f = f"{vid},{key:x}00002026"
        r = requests.put(f"http://{src.address}/{f}", data=data,
                         timeout=30)
        assert r.status_code in (200, 201), r.text
        blobs[f] = data
        total += size
        key += 1
    return vid, blobs, src


def _snapshot_dat(src, vid, tmp_path) -> str:
    """Flush + copy the volume's .dat for offline golden encoding."""
    v = src.store.find_volume(vid)
    with v._lock:
        v._sync_buffers()
    base = str(tmp_path / f"golden{vid}")
    with open(v.file_name() + ".dat", "rb") as fin, \
            open(base + ".dat", "wb") as fout:
        fout.write(fin.read())
    return base


def _golden_hashes(base, geo) -> list[str]:
    from seaweedfs_tpu.models.coder import new_coder

    ec_files.generate_ec_files(base, new_coder(10, 4), geo)
    out = []
    for i in range(geo.total_shards):
        with open(geo.shard_file_name(base, i), "rb") as f:
            out.append(hashlib.sha256(f.read()).hexdigest())
    return out


def _cluster_shard_hashes(volumes, vid, geo, collection) -> dict[int, str]:
    """shard id -> sha256, gathered from whichever server holds it."""
    out = {}
    for srv in volumes:
        for loc in srv.store.locations:
            for sid in range(geo.total_shards):
                p = geo.shard_file_name(loc.base_name(collection, vid),
                                        sid)
                if os.path.exists(p):
                    with open(p, "rb") as f:
                        out[sid] = hashlib.sha256(f.read()).hexdigest()
    return out


def _encode(env, vid, extra="") -> str:
    out = io.StringIO()
    code = run_command(env, f"ec.encode -volumeId {vid} {extra}", out)
    assert code == 0, out.getvalue()
    return out.getvalue()


def _wait_ec_registered(master, vid, timeout=10):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if vid in master.topo.ec_shard_map and vid not in {
                v for n in master.topo.nodes.values() for v in n.volumes}:
            return
        time.sleep(0.1)
    raise AssertionError(f"ec volume {vid} never registered")


# -- tentpole: streamed bit identity + in-process smoke ---------------------

def test_streamed_encode_bit_identity_and_reads(cluster, tmp_path):
    """Tier-1 smoke of the full stream->encode->remote-write->mount path:
    streamed shards hash-identical to an offline golden encode of the
    same .dat, spread across remote servers, and every needle reads back
    over HTTP through the EC serving path."""
    master, volumes, env = cluster
    vid, blobs, src = _make_volume(master, volumes, "strm", seed=1)
    base = _snapshot_dat(src, vid, tmp_path)
    golden = _golden_hashes(base, TEST_GEO)

    msg = _encode(env, vid, "-stream 1")
    assert "streamed" in msg and "overlap ratio" in msg, msg
    _wait_ec_registered(master, vid)

    got = _cluster_shard_hashes(volumes, vid, TEST_GEO, "strm")
    assert len(got) == TEST_GEO.total_shards, sorted(got)
    for sid, h in got.items():
        assert h == golden[sid], f"shard {sid} diverged from golden"

    # shards actually landed on REMOTE servers (not just the source)
    remote_holders = {s.address for s in volumes if s is not src
                     for loc in s.store.locations
                     for sid in range(TEST_GEO.total_shards)
                     if os.path.exists(TEST_GEO.shard_file_name(
                         loc.base_name("strm", vid), sid))}
    assert remote_holders, "no shard streamed to a remote server"

    # zero client-visible errors through the EC read path
    for fid, payload in blobs.items():
        r = requests.get(f"http://{src.address}/{fid}", timeout=30)
        assert r.status_code == 200, (fid, r.status_code)
        assert r.content == payload

    # stream metrics moved
    assert stats.EC_STREAM_BYTES.value(role="source", phase="live") > 0
    assert stats.EC_STREAM_STREAMS.value(outcome="ok") > 0


def test_copy_path_matches_golden_and_counts_fallback(cluster, tmp_path):
    """-stream 0 (generate-then-copy) produces the same golden bytes and
    moves the like-for-like VolumeEcShardsCopy byte counters."""
    master, volumes, env = cluster
    vid, blobs, src = _make_volume(master, volumes, "copy", seed=2)
    base = _snapshot_dat(src, vid, tmp_path)
    golden = _golden_hashes(base, TEST_GEO)

    before = stats.EC_COPY_FALLBACK_BYTES.value(kind="shard")
    _encode(env, vid, "-stream 0")
    _wait_ec_registered(master, vid)
    assert stats.EC_COPY_FALLBACK_BYTES.value(kind="shard") > before
    assert stats.EC_COPY_FALLBACK_SECONDS.value() > 0

    got = _cluster_shard_hashes(volumes, vid, TEST_GEO, "copy")
    assert len(got) == TEST_GEO.total_shards
    for sid, h in got.items():
        assert h == golden[sid], f"shard {sid} diverged from golden"
    for fid, payload in blobs.items():
        r = requests.get(f"http://{src.address}/{fid}", timeout=30)
        assert r.status_code == 200 and r.content == payload


# -- destination digests (.dig) ---------------------------------------------

def test_destination_digest_manifest_no_second_read(cluster, tmp_path):
    """Every streamed destination persists a `.dig` manifest whose folded
    CRCs equal a full-file CRC re-read, and VolumeDigest answers from
    it."""
    master, volumes, env = cluster
    vid, _blobs, src = _make_volume(master, volumes, "strm2", seed=3)
    _encode(env, vid, "-stream 1")
    _wait_ec_registered(master, vid)

    checked = 0
    for srv in volumes:
        if srv is src:
            continue
        for loc in srv.store.locations:
            base = loc.base_name("strm2", vid)
            if not os.path.exists(base + ".dig"):
                continue
            manifest = digest_mod.read_ec_manifest(base + ".dig")
            for sid, sc in manifest.items():
                path = TEST_GEO.shard_file_name(base, sid)
                with open(path, "rb") as f:
                    raw = f.read()
                assert len(raw) == sc.size
                assert crc32c(raw) == sc.crc, f"shard {sid} digest wrong"
                checked += 1
            # the VolumeDigest RPC serves these without a re-read
            stub = rpc.volume_stub(rpc.grpc_address(srv.address))
            from seaweedfs_tpu.pb import scrub_pb2

            resp = stub.VolumeDigest(
                scrub_pb2.VolumeDigestRequest(volume_id=vid), timeout=30)
            assert resp.is_ec
            got = {d.shard_id: (d.crc, d.size) for d in resp.shard_digests}
            for sid, sc in manifest.items():
                if sid in got:
                    assert got[sid] == (sc.crc, sc.size)
    assert checked > 0, "no destination manifest found"


def test_ec_manifest_format_golden():
    """Pin the on-disk EC digest manifest bytes."""
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        base = os.path.join(d, "7")
        digest_mod.write_ec_manifest(base, {
            1: digest_mod.ShardCrc(1, 0xDEADBEEF, 123),
            0: digest_mod.ShardCrc(0, 7, 0)})
        with open(base + ".dig", "rb") as f:
            blob = f.read()
    assert blob == (
        b"SWFSDGE\n" + (2).to_bytes(8, "big")
        + (0).to_bytes(4, "big") + (7).to_bytes(4, "big")
        + (0).to_bytes(8, "big")
        + (1).to_bytes(4, "big") + (0xDEADBEEF).to_bytes(4, "big")
        + (123).to_bytes(8, "big"))
    # round-trip through the file reader
    with tempfile.TemporaryDirectory() as d:
        p = os.path.join(d, "x.dig")
        with open(p, "wb") as f:
            f.write(blob)
        back = digest_mod.read_ec_manifest(p)
    assert back[1].crc == 0xDEADBEEF and back[1].size == 123
    assert back[0].crc == 7 and back[0].size == 0


# -- chaos: destination flap mid-stream + slab-range failpoint ---------------

def test_stream_resume_after_destination_flap(cluster, tmp_path):
    """Kill a destination mid-stream (ec.stream.slab failpoint): the
    source resumes from the destination's on-disk prefix, re-sends ONLY
    the missing range, final shards stay bit-identical, and the client
    sees zero errors."""
    master, volumes, env = cluster
    vid, blobs, src = _make_volume(master, volumes, "chaos", n_needles=40,
                                   seed=4, min_payload=140_000)
    base = _snapshot_dat(src, vid, tmp_path)
    dat_size = os.path.getsize(base + ".dat")
    # the stripe must cross the large-row boundary so slabs past offset
    # large_block exist (the flap target below)
    assert dat_size > TEST_GEO.large_block * TEST_GEO.data_shards, dat_size
    golden = _golden_hashes(base, TEST_GEO)

    resumes0 = stats.EC_STREAM_RESUMES.value()
    live0 = stats.EC_STREAM_BYTES.value(role="source", phase="live")
    resend0 = stats.EC_STREAM_BYTES.value(role="source", phase="resume")

    # one destination dies on the first small-row slab it sees (offset
    # large_block — AFTER every shard's first 10000 bytes landed), once;
    # the live stream to it aborts and the resume must start from the
    # on-disk prefix, never re-sending the completed large-row slabs
    with failpoint.active("ec.stream.slab", p=1.0, count=1,
                          match=f"off={TEST_GEO.large_block},") as fp:
        msg = _encode(env, vid, "-stream 1")
        assert fp.hits == 1, "destination never flapped"
    assert "resume" in msg, msg
    _wait_ec_registered(master, vid)

    assert stats.EC_STREAM_RESUMES.value() > resumes0
    resent = stats.EC_STREAM_BYTES.value(role="source",
                                         phase="resume") - resend0
    live = stats.EC_STREAM_BYTES.value(role="source", phase="live") - live0
    shard_size = TEST_GEO.shard_size(dat_size)
    total_shard_bytes = shard_size * TEST_GEO.total_shards
    assert resent > 0
    # only the missing tail ranges were re-sent: the flapped destination
    # already held every shard's large-row prefix, so the resume is far
    # smaller than even one destination's full share
    assert resent < total_shard_bytes / 2, (resent, total_shard_bytes)
    assert live > resent, (live, resent)

    got = _cluster_shard_hashes(volumes, vid, TEST_GEO, "chaos")
    assert len(got) == TEST_GEO.total_shards
    for sid, h in got.items():
        assert h == golden[sid], f"shard {sid} diverged after resume"
    for fid, payload in blobs.items():
        r = requests.get(f"http://{src.address}/{fid}", timeout=30)
        assert r.status_code == 200 and r.content == payload


def test_stream_slab_failpoint_matches_shard_and_range(cluster):
    """The ec.stream.slab ctx is matchable per shard AND per slab offset
    (comma-terminated, so shard=1 can't substring-hit shard 10)."""
    # grammar: the comma-terminated ctx cannot substring-collide
    fp = failpoint._Failpoint("ec.stream.slab", "error", 1.0, -1,
                              "shard=1, off=0,", None)
    assert fp.should_trigger("localhost:1, shard=1, off=0,")
    assert not fp.should_trigger("localhost:1, shard=10, off=0,")
    assert not fp.should_trigger("localhost:1, shard=1, off=10000,")

    # live: target the first slab of ANY shard at a remote destination
    # (alternative grammar), once — the stream resumes and converges
    master, volumes, env = cluster
    vid, _blobs, _src = _make_volume(master, volumes, "slab", seed=5)
    alts = "|".join(f"shard={i}, off=0," for i in range(14))
    with failpoint.active("ec.stream.slab", p=1.0, count=1,
                          match=alts) as live:
        _encode(env, vid, "-stream 1")
        assert live.hits == 1, "no targeted slab hit the failpoint"


def test_stream_hard_failure_falls_back_to_copy(cluster):
    """A destination that refuses every stream (failpoint without a
    count bound) is completed via the VolumeEcShardsCopy fallback —
    the archive still converges."""
    master, volumes, env = cluster
    vid, blobs, src = _make_volume(master, volumes, "fall", seed=6)
    old = os.environ.get("SWFS_EC_STREAM_RETRIES")
    os.environ["SWFS_EC_STREAM_RETRIES"] = "2"
    try:
        # no @match: EVERY destination refuses every slab (placement may
        # give any particular server zero shards, so targeting one
        # address can vacuously miss)
        with failpoint.active("ec.stream.slab", p=1.0):
            msg = _encode(env, vid, "-stream 1")
    finally:
        if old is None:
            os.environ.pop("SWFS_EC_STREAM_RETRIES", None)
        else:
            os.environ["SWFS_EC_STREAM_RETRIES"] = old
    assert "fallback copy" in msg, msg
    _wait_ec_registered(master, vid)
    for fid, payload in blobs.items():
        r = requests.get(f"http://{src.address}/{fid}", timeout=30)
        assert r.status_code == 200 and r.content == payload


# -- satellite: read-only rollback on failed encode --------------------------

@pytest.mark.parametrize("stream,fp_name", [
    (1, "pb.VolumeEcShardsGenerateStreamed"),
    (0, "pb.VolumeEcShardsGenerate"),
])
def test_failed_encode_rolls_back_readonly(cluster, stream, fp_name):
    """Regression (pre-ISSUE-6 bug): a generate/copy/mount failure left
    every replica read-only forever. Now the replicas are restored to
    writable and the volume keeps serving."""
    master, volumes, env = cluster
    vid, blobs, src = _make_volume(master, volumes, "roll", seed=7 + stream)
    v = src.store.find_volume(vid)
    assert not v.read_only
    with failpoint.active(fp_name, p=1.0, count=1):
        out = io.StringIO()
        code = run_command(env, f"ec.encode -volumeId {vid} "
                                f"-stream {stream}", out)
        assert code != 0, "encode unexpectedly succeeded"
    assert not v.read_only, "replica left read-only after failed encode"
    # the plain volume still serves
    fid, payload = next(iter(blobs.items()))
    r = requests.get(f"http://{src.address}/{fid}", timeout=30)
    assert r.status_code == 200 and r.content == payload
    # and a retry without the failpoint completes the conversion
    _encode(env, vid, f"-stream {stream}")
    _wait_ec_registered(master, vid)


# -- observability ------------------------------------------------------------

def test_status_and_metrics_expose_ec_stream(cluster):
    master, volumes, _env = cluster
    st = requests.get(f"http://{volumes[0].address}/status",
                      timeout=10).json()
    assert "EcStream" in st
    sect = st["EcStream"]
    for key in ("streamedBytes", "inflightBytes", "resumes", "streams",
                "overlapRatio", "copyFallback"):
        assert key in sect, sect
    text = requests.get(f"http://{volumes[0].address}/metrics",
                        timeout=10).text
    assert "SeaweedFS_ec_stream_bytes" in text
    assert "SeaweedFS_ec_shards_copy_bytes" in text
