"""Concurrency stress suite — the framework's race-detection analogue
(SURVEY.md §5.2). The reference leans on `go test -race` over its heavily
goroutine'd code; Python has no sanitizer, so this suite hammers the
shared-state hot paths from many threads and asserts invariants that any
interleaving must preserve:

  * needle isolation: a read returns the exact bytes written for that fid
    (or a clean 404 after delete) — never another writer's payload
  * index/data agreement after the storm (volume check_and_fix clean)
  * filer namespace consistency under concurrent create/rename/delete
  * upload-pipeline byte integrity under reader/writer/spill contention
"""

import hashlib
import socket
import threading
import time

import numpy as np
import pytest
import requests

from seaweedfs_tpu.operation import assign, upload_data
from seaweedfs_tpu.pb import rpc
from seaweedfs_tpu.server.master import MasterServer
from seaweedfs_tpu.server.volume import VolumeServer
from seaweedfs_tpu.storage.file_id import parse_file_id

THREADS = 8
OPS_PER_THREAD = 40


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    mport = _free_port()
    master = MasterServer(ip="localhost", port=mport, volume_size_limit_mb=64)
    master.start(vacuum_interval=3600)
    vsrv = VolumeServer(directories=[str(tmp_path_factory.mktemp("vol"))],
                        master=f"localhost:{mport}", ip="localhost",
                        port=_free_port(), pulse_seconds=1)
    vsrv.start()
    deadline = time.time() + 10
    while time.time() < deadline and not master.topo.nodes:
        time.sleep(0.05)
    yield master, vsrv
    vsrv.stop()
    master.stop()
    rpc.reset_channels()


def _run_threads(fn, n=THREADS):
    errors: list[BaseException] = []

    def wrapped(tid):
        try:
            fn(tid)
        except BaseException as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=wrapped, args=(t,)) for t in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if errors:
        raise errors[0]


def test_volume_write_read_delete_storm(cluster):
    """Concurrent writers/readers/deleters on one server: every read sees
    its own payload or a 404 — never crossed wires — and the needle index
    agrees with the data file afterwards."""
    master, vsrv = cluster
    session = requests.Session()
    written: dict[str, bytes] = {}
    written_lock = threading.Lock()
    rng_global = np.random.default_rng(1234)
    seeds = rng_global.integers(0, 2**31, size=THREADS)

    def worker(tid):
        rng = np.random.default_rng(seeds[tid])
        mine: list[tuple[str, bytes]] = []
        for i in range(OPS_PER_THREAD):
            op = rng.integers(0, 10)
            if op < 6 or not mine:  # write
                payload = (f"t{tid}i{i}:".encode()
                           + rng.integers(0, 256, int(rng.integers(100, 8000)),
                                          dtype=np.uint8).tobytes())
                a = assign(master.address)
                assert not a.error, a.error
                r = upload_data(f"http://{a.url}/{a.fid}", payload)
                assert not r.error, r.error
                mine.append((a.fid, payload))
                with written_lock:
                    written[a.fid] = payload
            elif op < 9:  # read one of ours
                fid, payload = mine[int(rng.integers(0, len(mine)))]
                resp = session.get(f"http://{vsrv.address}/{fid}", timeout=30)
                if resp.status_code == 200:
                    assert resp.content == payload, f"crossed wires on {fid}"
                else:
                    assert resp.status_code == 404  # deleted by us earlier
            else:  # delete one of ours
                fid, _ = mine.pop(int(rng.integers(0, len(mine))))
                session.delete(f"http://{vsrv.address}/{fid}", timeout=30)
                with written_lock:
                    written.pop(fid, None)

    _run_threads(worker)

    # post-storm: all surviving fids readable with exact bytes
    for fid, payload in written.items():
        r = session.get(f"http://{vsrv.address}/{fid}", timeout=30)
        assert r.status_code == 200 and r.content == payload, fid

    # index/data agreement on every volume touched: the startup integrity
    # scan must find nothing to truncate (a torn/interleaved append would
    # shrink file_count)
    for loc in vsrv.store.locations:
        for vid, v in list(loc.volumes.items()):
            before = v.file_count()
            v.check_and_fix_integrity()
            assert v.file_count() == before, f"volume {vid} lost records"


def test_filer_namespace_storm(tmp_path_factory):
    """Concurrent create/rename/delete on one Filer: no lost updates — the
    final namespace equals the union of surviving per-thread files, and
    every surviving file's content is its writer's."""
    from seaweedfs_tpu.filer import Entry, Filer
    from seaweedfs_tpu.filer.filerstore import get_store

    f = Filer(get_store("sqlite", db_path=str(
        tmp_path_factory.mktemp("ns") / "f.db")))
    survivors: dict[str, bytes] = {}
    lock = threading.Lock()

    def worker(tid):
        base = f"/storm/t{tid}"
        mine = []
        for i in range(OPS_PER_THREAD):
            path = f"{base}/file{i}.txt"
            body = f"payload-{tid}-{i}".encode()
            f.create_entry(Entry(full_path=path, content=body))
            mine.append((path, body))
            if i % 7 == 3:  # rename a quarter of them
                old, body2 = mine.pop()
                new = f"{base}/renamed{i}.txt"
                f.rename(old, new)
                mine.append((new, body2))
            if i % 11 == 5 and mine:  # delete some
                victim, _ = mine.pop(0)
                f.delete_entry(victim)
        with lock:
            survivors.update(dict(mine))

    _run_threads(worker)

    for path, body in survivors.items():
        got = f.find_entry(path)
        assert got is not None, f"lost update: {path}"
        assert got.content == body, f"content mixed up: {path}"
    # directory listings agree with point lookups
    for tid in range(THREADS):
        listed = {e.full_path for e in f.list_entries(f"/storm/t{tid}")}
        expect = {p for p in survivors if p.startswith(f"/storm/t{tid}/")}
        assert listed == expect
    f.store.close()


def test_upload_pipeline_reader_writer_spill_storm(tmp_path):
    """Readers racing writers and the uploader across the spill boundary:
    reads-before-flush always reflect the latest write for that region."""
    from seaweedfs_tpu.mount.page_writer import MemBudget, UploadPipeline

    chunk = 4096
    gate = threading.Event()
    uploaded = {}

    save_lock = threading.Lock()

    def slow_save(data, offset, ts):
        gate.wait(20)
        with save_lock:  # keep the newest stamp per region (uploads of
            # successive sealed generations finish in any order)
            if offset not in uploaded or uploaded[offset][0] < ts:
                uploaded[offset] = (ts, data)

    p = UploadPipeline(chunk, slow_save, concurrency=2,
                       budget=MemBudget(2), swap_dir=str(tmp_path))
    region_vals: dict[int, int] = {}
    vals_lock = threading.Lock()
    stop = threading.Event()
    read_errors = []

    def writer(tid):
        rng = np.random.default_rng(tid)
        for i in range(OPS_PER_THREAD):
            region = int(rng.integers(0, 16))
            stamp = (tid << 16) | i
            blob = stamp.to_bytes(4, "big") * (chunk // 4)
            with vals_lock:
                p.save_data_at(blob, region * chunk, time.time_ns())
                region_vals[region] = stamp

    def reader():
        rng = np.random.default_rng(999)
        buf = memoryview(bytearray(chunk))
        while not stop.is_set():
            region = int(rng.integers(0, 16))
            with vals_lock:
                want = region_vals.get(region)
                covered = p.maybe_read_data_at(buf, region * chunk)
                if want is not None and covered == [(0, chunk)]:
                    got = int.from_bytes(bytes(buf[:4]), "big")
                    if got != want:
                        read_errors.append((region, want, got))

    rt = threading.Thread(target=reader)
    rt.start()
    try:
        _run_threads(writer, n=4)
    finally:
        stop.set()
        rt.join()
        gate.set()
    p.flush()
    assert not read_errors, read_errors[:3]
    assert p.swapped_out > 0, "storm never hit the spill path"
    # newest generation wins per region in the uploaded bytes
    for region, stamp in region_vals.items():
        assert uploaded[region * chunk][1][:4] == stamp.to_bytes(4, "big")
    p.close()


def test_mem_budget_never_negative_under_churn(tmp_path):
    from seaweedfs_tpu.mount.page_writer import MemBudget, UploadPipeline

    budget = MemBudget(4)

    def churn(tid):
        p = UploadPipeline(256, lambda d, o, t: None, concurrency=2,
                           budget=budget, swap_dir=str(tmp_path))
        for i in range(OPS_PER_THREAD):
            p.save_data_at(b"x" * 256, (i % 8) * 256, i)
        p.flush()
        p.close()

    _run_threads(churn)
    assert 0 <= budget._held <= budget.limit, budget._held
    # all capacity is back
    takes = sum(1 for _ in range(4) if budget.try_take())
    assert takes == 4
