"""tools/lint.py wired into tier-1 as a fast pre-test gate (ISSUE 2
satellite): the whole tree must pass the pinned minimal rule set
(E9/F63/F7/F82 under ruff; the built-in syntax+comparison fallback when
ruff isn't installed) before the functional suite spends its budget."""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_lint_gate_is_clean():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "lint.py")],
        cwd=REPO, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, \
        f"lint findings:\n{proc.stdout}\n{proc.stderr}"


def _load_lint():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "swfs_lint", os.path.join(REPO, "tools", "lint.py"))
    lint = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(lint)
    return lint


def test_lint_catches_syntax_error(tmp_path):
    """The gate actually gates: a file that cannot compile fails it."""
    bad = tmp_path / "pkg"
    bad.mkdir()
    (bad / "broken.py").write_text("def f(:\n    pass\n")
    lint = _load_lint()

    files = [str(bad / "broken.py")]
    orig = lint._python_files
    lint._python_files = lambda: files
    try:
        assert lint.run_fallback() == 1
    finally:
        lint._python_files = orig


def test_lint_catches_bare_device_enumeration(tmp_path):
    """SWFS001 (ISSUE 5 satellite): bare jax.devices() outside the mesh
    helpers is an error — device placement must go through
    parallel/mesh.py — while the allow-listed files stay exempt."""
    lint = _load_lint()
    bad = tmp_path / "stray.py"
    bad.write_text(
        "import jax\n"
        "def pick():\n"
        "    return jax.local_devices()[0] or jax.devices()\n")
    findings = lint.run_device_rule([str(bad)])
    assert len(findings) == 2 and all("SWFS001" in f for f in findings), \
        findings

    # the sanctioned enumeration point itself must stay exempt
    mesh_path = os.path.join(REPO, "seaweedfs_tpu", "parallel", "mesh.py")
    assert lint.run_device_rule([mesh_path]) == []

    # and the rule runs as part of the gate regardless of ruff presence:
    # the repo itself is clean under it
    assert lint.run_device_rule() == []


def test_lint_catches_wall_clock_in_trace_plane(tmp_path):
    """SWFS002 (ISSUE 7 satellite): `time.time()` / `time.time_ns()`
    inside the tracing plane is an error — span timing must be
    monotonic — while the marked module-level anchor stays exempt."""
    lint = _load_lint()
    bad = tmp_path / "trace.py"
    bad.write_text(
        "import time\n"
        "ANCHOR = time.time_ns() / 1e9  # lint: allow-wall-clock-anchor\n"
        "def span_start():\n"
        "    return time.time()\n"
        "def span_stamp():\n"
        "    return time.time_ns()\n"
        "def fine():\n"
        "    return time.perf_counter() + time.monotonic()\n")
    findings = lint.run_span_timing_rule([str(bad)])
    assert len(findings) == 2 and all("SWFS002" in f for f in findings), \
        findings

    # the real tracing module is clean under the rule (its single
    # wall-clock read is the marked anchor)
    assert lint.run_span_timing_rule() == []


def test_executor_marker_cannot_bless_adjacent_unrelated_call(tmp_path):
    """ISSUE 15 satellite: the old allow-marker blessed `range(i+1,
    i+6)` — five arbitrary lines — so a marker above a short `with`
    also exempted whatever statement followed it. The span now comes
    from the AST: a second, unmarked ThreadPoolExecutor immediately
    after a marked one must still be reported."""
    lint = _load_lint()
    bad = tmp_path / "adjacent.py"
    bad.write_text(
        "from concurrent.futures import ThreadPoolExecutor\n"
        "def blessed_then_not(items):\n"
        "    # lint: allow-executor(startup-only, joined at exit)\n"
        "    ex1 = ThreadPoolExecutor(max_workers=2)\n"
        "    ex2 = ThreadPoolExecutor(max_workers=2)\n"
        "    return ex1, ex2\n")
    findings = lint.run_executor_rule([str(bad)])
    assert len(findings) == 1 and ":5:" in findings[0], findings

    # a marker TRAILING a code line blesses that statement only — it
    # must not open a "comment block" that exempts the next line too
    trail = tmp_path / "trailing.py"
    trail.write_text(
        "from concurrent.futures import ThreadPoolExecutor\n"
        "def t(items):\n"
        "    ex1 = ThreadPoolExecutor(2)  "
        "# lint: allow-executor(startup pool)\n"
        "    ex2 = ThreadPoolExecutor(2)\n"
        "    return ex1, ex2\n")
    findings = lint.run_executor_rule([str(trail)])
    assert len(findings) == 1 and ":4:" in findings[0], findings

    # a marker whose justification comment block runs down TO the
    # statement still blesses it (the shipped multi-line form) …
    multi = tmp_path / "multiline.py"
    multi.write_text(
        "from concurrent.futures import ThreadPoolExecutor\n"
        "def blessed(items):\n"
        "    # lint: allow-executor — scoped pool whose exit joins\n"
        "    # the stragglers; bounded by the shard count\n"
        "    with ThreadPoolExecutor(max_workers=2) as ex:\n"
        "        return list(ex.map(str, items))\n")
    assert lint.run_executor_rule([str(multi)]) == []

    # … and a marker with NO reason at all still gates
    bare = tmp_path / "bare.py"
    bare.write_text(
        "from concurrent.futures import ThreadPoolExecutor\n"
        "def marked_but_unjustified(items):\n"
        "    # lint: allow-executor\n"
        "    with ThreadPoolExecutor(max_workers=2) as ex:\n"
        "        return list(ex.map(str, items))\n")
    findings = lint.run_executor_rule([str(bare)])
    assert len(findings) == 1 and "no reason" in findings[0], findings


def test_lint_catches_silent_broad_except(tmp_path):
    """SWFS004 (ISSUE 15): `except Exception` that neither logs,
    counts, re-raises, nor uses the bound exception is a silent
    swallow; observing handlers and justified markers stay exempt."""
    lint = _load_lint()
    bad = tmp_path / "swallow.py"
    bad.write_text(
        "import glog\n"
        "def silent():\n"
        "    try:\n"
        "        work()\n"
        "    except Exception:\n"
        "        return None\n"
        "def bare():\n"
        "    try:\n"
        "        work()\n"
        "    except:\n"
        "        pass\n"
        "def logs():\n"
        "    try:\n"
        "        work()\n"
        "    except Exception as e:\n"
        "        glog.warning(f'failed: {e}')\n"
        "def reraises():\n"
        "    try:\n"
        "        work()\n"
        "    except Exception:\n"
        "        raise\n"
        "def uses_bound():\n"
        "    try:\n"
        "        work()\n"
        "    except Exception as e:\n"
        "        return {'error': str(e)}\n"
        "def justified():\n"
        "    try:\n"
        "        work()\n"
        "    # lint: allow-broad-except(capability probe; absence is\n"
        "    # the answer)\n"
        "    except Exception:\n"
        "        return False\n"
        "def narrow():\n"
        "    try:\n"
        "        work()\n"
        "    except ValueError:\n"
        "        return None\n")
    findings = lint.run_broad_except_rule([str(bad)])
    assert len(findings) == 2 and all("SWFS004" in f for f in findings), \
        findings
    assert ":5:" in findings[0] and ":10:" in findings[1], findings

    # the gated packages themselves are clean (every surviving broad
    # except observes the failure or carries a written justification)
    assert lint.run_broad_except_rule() == []


def test_lint_catches_blocking_call_under_named_lock(tmp_path):
    """SWFS005 (ISSUE 15): sleeps, HTTP legs, RPC stubs, untimed
    queue.get()/Event.wait() and future.result() reached while a named
    lock is held are errors; timeouts and justified sites pass."""
    lint = _load_lint()
    bad = tmp_path / "stall.py"
    bad.write_text(
        "import queue\n"
        "import threading\n"
        "import time\n"
        "import requests\n"
        "class Srv:\n"
        "    def __init__(self):\n"
        "        self._mu = threading.Lock()\n"
        "        self._q = queue.Queue()\n"
        "        self._ev = threading.Event()\n"
        "    def stalls(self, stub, fut):\n"
        "        with self._mu:\n"
        "            time.sleep(1)\n"
        "            requests.get('http://peer/ping')\n"
        "            stub.VolumeDigest(None)\n"
        "            self._q.get()\n"
        "            self._ev.wait()\n"
        "            fut.result()\n"
        "    def fine(self, fut):\n"
        "        with self._mu:\n"
        "            self._q.get(timeout=1.0)\n"
        "            self._ev.wait(0.5)\n"
        "            fut.result(timeout=2)\n"
        "            self._q.get_nowait()\n"
        "        time.sleep(1)\n"
        "    def justified(self):\n"
        "        with self._mu:\n"
        "            # lint: allow-blocking-under-lock(bounded 10ms\n"
        "            # settle; callers tolerate it)\n"
        "            time.sleep(0.01)\n"
        "    def one_level_deep(self):\n"
        "        with self._mu:\n"
        "            self._helper()\n"
        "    def _helper(self):\n"
        "        time.sleep(5)\n")
    findings = lint.run_blocking_rule([str(bad)])
    assert len(findings) == 7 and all("SWFS005" in f for f in findings), \
        findings
    lines = sorted(int(f.split(":")[1]) for f in findings)
    assert lines == [12, 13, 14, 15, 16, 17, 32], findings
    assert any("_helper" in f and "callee blocks" in f for f in findings)

    # Condition(self._mu): waiting on the cv RELEASES _mu even though
    # the held stack carries it under the wrapped lock's canonical
    # name — no finding; holding a DIFFERENT lock across the wait is
    wrapped = tmp_path / "wrapped_cv.py"
    wrapped.write_text(
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._mu = threading.Lock()\n"
        "        self._cond = threading.Condition(self._mu)\n"
        "        self._other = threading.Lock()\n"
        "    def fine(self):\n"
        "        with self._cond:\n"
        "            self._cond.wait()\n"
        "    def stalls(self):\n"
        "        with self._other:\n"
        "            with self._cond:\n"
        "                self._cond.wait()\n")
    findings = lint.run_blocking_rule([str(wrapped)])
    assert len(findings) == 1 and ":13:" in findings[0] \
        and "_other" in findings[0], findings

    # the product tree is clean under the rule today — a regression
    # here means a new blocking call crept under a named lock
    assert lint.run_blocking_rule() == []


def test_lint_catches_lock_order_cycle(tmp_path):
    """LOCKGRAPH (ISSUE 15 tentpole): an ABBA pair — including one arm
    hidden behind a method call one level deep — is a cycle; consistent
    ordering and per-instance same-name nesting are not."""
    lint = _load_lint()
    bad = tmp_path / "abba.py"
    bad.write_text(
        "import threading\n"
        "A = threading.Lock()\n"
        "class Gc:\n"
        "    def __init__(self):\n"
        "        self._mu = threading.RLock()\n"
        "    def forward(self):\n"
        "        with self._mu:\n"
        "            with A:\n"
        "                pass\n"
        "    def backward(self):\n"
        "        with A:\n"
        "            self._take_mu()\n"
        "    def _take_mu(self):\n"
        "        with self._mu:\n"
        "            pass\n")
    findings = lint.run_lockgraph_rule([str(bad)])
    assert len(findings) == 1 and "LOCKGRAPH" in findings[0] \
        and "cycle" in findings[0], findings
    assert "Gc._mu" in findings[0] and ":A" in findings[0]

    ok = tmp_path / "ordered.py"
    ok.write_text(
        "import threading\n"
        "A = threading.Lock()\n"
        "class Gc:\n"
        "    def __init__(self):\n"
        "        self._mu = threading.Lock()\n"
        "    def f(self):\n"
        "        with self._mu:\n"
        "            with A:\n"
        "                pass\n"
        "    def g(self):\n"
        "        with self._mu:\n"
        "            with A:\n"
        "                pass\n")
    assert lint.run_lockgraph_rule([str(ok)]) == []

    # the repo's own whole-program graph is acyclic
    assert lint.run_lockgraph_rule() == []


def test_lint_json_output_is_machine_readable():
    """ISSUE 15 satellite: `tools/lint.py --json` emits rule id, path,
    line, message and marker status for every finding (blessed ones
    included, so CI can diff both counts across PRs); exit code
    matches the text mode."""
    import json

    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "lint.py"),
         "--json"],
        cwd=REPO, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    out = json.loads(proc.stdout)
    assert set(out) >= {"findings", "active", "allowed", "by_rule"}
    assert out["active"] == 0  # text mode exits 0 ⇒ no active findings
    assert out["allowed"] >= 10  # the triaged justification markers
    for f in out["findings"]:
        assert set(f) == {"rule", "path", "line", "message", "marker",
                          "reason"}
        assert f["marker"] == "allowed" and f["reason"], f


def test_lint_findings_never_exceed_baseline():
    """ISSUE 16 satellite: a RATCHET on the marker-blessed debt. The
    active-findings gate above keeps un-blessed findings at zero, but
    nothing stopped a PR from quietly growing the *allowed* pile by
    pasting justification markers. LINT_BASELINE.json pins the per-rule
    ceiling; exceeding it fails, shrinking it should lower the baseline
    in the same PR (asymmetric on purpose — improvements are free)."""
    import json

    with open(os.path.join(REPO, "LINT_BASELINE.json")) as f:
        baseline = json.load(f)["by_rule"]
    lint = _load_lint()
    counts: dict[str, int] = {}
    for finding in lint.custom_findings():
        counts[finding.rule] = counts.get(finding.rule, 0) + 1
    over = {rule: (n, baseline.get(rule, 0))
            for rule, n in counts.items() if n > baseline.get(rule, 0)}
    assert not over, (
        "lint debt grew past LINT_BASELINE.json (rule: found > ceiling) "
        f"{ {r: f'{n} > {b}' for r, (n, b) in over.items()} } — fix the "
        "new finding or, if genuinely justified, raise the baseline "
        "with an explanation in the PR")


def test_lint_baseline_history_archives_per_pr_counts(tmp_path):
    """ISSUE 17 satellite (ROADMAP 7c): `--archive-baseline <label>`
    appends the tree's per-rule counts to LINT_BASELINE.json `history`
    so CI can diff the series per PR instead of only ceiling-checking.
    The committed history must be well-formed, and the archiver must be
    idempotent per label (CI retries re-archive the same PR)."""
    import json
    import shutil

    lint = _load_lint()
    with open(os.path.join(REPO, "LINT_BASELINE.json")) as f:
        base = json.load(f)
    assert base["history"], "LINT_BASELINE.json history must be seeded"
    for e in base["history"]:
        assert set(e) == {"label", "by_rule"}, e
        assert all(isinstance(n, int) and n >= 0
                   for n in e["by_rule"].values()), e
    # mechanism, against a scratch copy: append, overwrite-in-place on a
    # repeated label, preserve order — counts exactly custom_findings()
    path = tmp_path / "LINT_BASELINE.json"
    shutil.copy(os.path.join(REPO, "LINT_BASELINE.json"), path)
    counts: dict[str, int] = {}
    for finding in lint.custom_findings():
        counts[finding.rule] = counts.get(finding.rule, 0) + 1
    before = len(base["history"])
    entry = lint.archive_baseline("PRTEST", str(path))
    assert entry["by_rule"] == dict(sorted(counts.items()))
    lint.archive_baseline("PRTEST", str(path))  # idempotent re-archive
    with open(path) as f:
        hist = json.load(f)["history"]
    assert len(hist) == before + 1
    assert hist[-1] == {"label": "PRTEST",
                        "by_rule": dict(sorted(counts.items()))}


def test_every_swfs_knob_is_documented_in_readme():
    """ISSUE 15 satellite (mirror of the metrics-table test): every
    SWFS_* env knob the package reads must appear in README.md; the
    failure message carries the generated inventory lines to paste."""
    lint = _load_lint()
    knobs = lint.knob_inventory()
    assert len(knobs) >= 40  # the inventory actually walked the tree
    assert "SWFS_LOCK_WITNESS" in knobs
    readme = open(os.path.join(REPO, "README.md")).read()
    missing = {k: v for k, v in knobs.items() if k not in readme}
    assert not missing, (
        "undocumented SWFS_* knobs — seed README from this inventory:\n"
        + "\n".join(lint._knobs.inventory_lines(missing)))


def test_lint_catches_bare_executor_on_serving_paths(tmp_path):
    """SWFS003 (ISSUE 14 satellite): bare ThreadPoolExecutor
    construction inside server/ + filer/ is an error — fan-out belongs
    on the shared bounded executor (utils/fanout.py) — while sites
    carrying the `lint: allow-executor` justification stay exempt."""
    lint = _load_lint()
    bad = tmp_path / "hotpath.py"
    bad.write_text(
        "from concurrent.futures import ThreadPoolExecutor\n"
        "import concurrent.futures as cf\n"
        "def fan(items):\n"
        "    with ThreadPoolExecutor(max_workers=4) as ex:\n"
        "        return list(ex.map(str, items))\n"
        "def fan2(items):\n"
        "    with cf.ThreadPoolExecutor(max_workers=4) as ex:\n"
        "        return list(ex.map(str, items))\n"
        "def blessed(items):\n"
        "    # lint: allow-executor — startup-only, joined at exit\n"
        "    with ThreadPoolExecutor(max_workers=4) as ex:\n"
        "        return list(ex.map(str, items))\n")
    findings = lint.run_executor_rule([str(bad)])
    assert len(findings) == 2 and all("SWFS003" in f for f in findings), \
        findings

    # the serving packages themselves are clean under the rule (every
    # remaining scoped pool carries its justification marker)
    assert lint.run_executor_rule() == []
