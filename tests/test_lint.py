"""tools/lint.py wired into tier-1 as a fast pre-test gate (ISSUE 2
satellite): the whole tree must pass the pinned minimal rule set
(E9/F63/F7/F82 under ruff; the built-in syntax+comparison fallback when
ruff isn't installed) before the functional suite spends its budget."""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_lint_gate_is_clean():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "lint.py")],
        cwd=REPO, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, \
        f"lint findings:\n{proc.stdout}\n{proc.stderr}"


def _load_lint():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "swfs_lint", os.path.join(REPO, "tools", "lint.py"))
    lint = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(lint)
    return lint


def test_lint_catches_syntax_error(tmp_path):
    """The gate actually gates: a file that cannot compile fails it."""
    bad = tmp_path / "pkg"
    bad.mkdir()
    (bad / "broken.py").write_text("def f(:\n    pass\n")
    lint = _load_lint()

    files = [str(bad / "broken.py")]
    orig = lint._python_files
    lint._python_files = lambda: files
    try:
        assert lint.run_fallback() == 1
    finally:
        lint._python_files = orig


def test_lint_catches_bare_device_enumeration(tmp_path):
    """SWFS001 (ISSUE 5 satellite): bare jax.devices() outside the mesh
    helpers is an error — device placement must go through
    parallel/mesh.py — while the allow-listed files stay exempt."""
    lint = _load_lint()
    bad = tmp_path / "stray.py"
    bad.write_text(
        "import jax\n"
        "def pick():\n"
        "    return jax.local_devices()[0] or jax.devices()\n")
    findings = lint.run_device_rule([str(bad)])
    assert len(findings) == 2 and all("SWFS001" in f for f in findings), \
        findings

    # the sanctioned enumeration point itself must stay exempt
    mesh_path = os.path.join(REPO, "seaweedfs_tpu", "parallel", "mesh.py")
    assert lint.run_device_rule([mesh_path]) == []

    # and the rule runs as part of the gate regardless of ruff presence:
    # the repo itself is clean under it
    assert lint.run_device_rule() == []


def test_lint_catches_wall_clock_in_trace_plane(tmp_path):
    """SWFS002 (ISSUE 7 satellite): `time.time()` / `time.time_ns()`
    inside the tracing plane is an error — span timing must be
    monotonic — while the marked module-level anchor stays exempt."""
    lint = _load_lint()
    bad = tmp_path / "trace.py"
    bad.write_text(
        "import time\n"
        "ANCHOR = time.time_ns() / 1e9  # lint: allow-wall-clock-anchor\n"
        "def span_start():\n"
        "    return time.time()\n"
        "def span_stamp():\n"
        "    return time.time_ns()\n"
        "def fine():\n"
        "    return time.perf_counter() + time.monotonic()\n")
    findings = lint.run_span_timing_rule([str(bad)])
    assert len(findings) == 2 and all("SWFS002" in f for f in findings), \
        findings

    # the real tracing module is clean under the rule (its single
    # wall-clock read is the marked anchor)
    assert lint.run_span_timing_rule() == []


def test_lint_catches_bare_executor_on_serving_paths(tmp_path):
    """SWFS003 (ISSUE 14 satellite): bare ThreadPoolExecutor
    construction inside server/ + filer/ is an error — fan-out belongs
    on the shared bounded executor (utils/fanout.py) — while sites
    carrying the `lint: allow-executor` justification stay exempt."""
    lint = _load_lint()
    bad = tmp_path / "hotpath.py"
    bad.write_text(
        "from concurrent.futures import ThreadPoolExecutor\n"
        "import concurrent.futures as cf\n"
        "def fan(items):\n"
        "    with ThreadPoolExecutor(max_workers=4) as ex:\n"
        "        return list(ex.map(str, items))\n"
        "def fan2(items):\n"
        "    with cf.ThreadPoolExecutor(max_workers=4) as ex:\n"
        "        return list(ex.map(str, items))\n"
        "def blessed(items):\n"
        "    # lint: allow-executor — startup-only, joined at exit\n"
        "    with ThreadPoolExecutor(max_workers=4) as ex:\n"
        "        return list(ex.map(str, items))\n")
    findings = lint.run_executor_rule([str(bad)])
    assert len(findings) == 2 and all("SWFS003" in f for f in findings), \
        findings

    # the serving packages themselves are clean under the rule (every
    # remaining scoped pool carries its justification marker)
    assert lint.run_executor_rule() == []
