"""Host memory plane suite (ISSUE 12): stack-arena recycling
correctness under concurrent pipelines, zero-fill elision, quarantined
release on async (jax) backends, O(1) steady-state dispatch-path
allocations, NUMA pinning plumbing, and the scrub fadvise satellite.

The load-bearing property is the same as ISSUE 3's: the arena may change
WHERE a flush's bytes are staged, never what they compute — shard bytes
are pinned identical arena-on / arena-off / all backends, including
while buffers are being recycled under concurrent encode + reconstruct
pipelines.
"""

import os
import threading
import tracemalloc

import numpy as np
import pytest

from seaweedfs_tpu.models.coder import new_coder
from seaweedfs_tpu.ops import dispatch
from seaweedfs_tpu.ops.rs_cpu import RSCodecCPU
from seaweedfs_tpu.storage import ec_files
from seaweedfs_tpu.storage.ec_locate import Geometry
from seaweedfs_tpu.utils import numa, stats

TEST_GEO = Geometry(large_block=10000, small_block=100)


@pytest.fixture(autouse=True)
def _clean_schedulers():
    yield
    dispatch.shutdown_all()


def _arena_count(result: str) -> int:
    return int(stats.EC_DISPATCH_ARENA_OPS.value(result=result))


def _make_volume(base, seed=0, n_needles=40):
    import sys

    sys.path.insert(0, os.path.dirname(__file__))
    from test_ec_pipeline import _make_synthetic_volume

    _make_synthetic_volume(base, seed=seed, n_needles=n_needles)


# -- arena unit behavior -----------------------------------------------------


def test_arena_pool_recycles_and_bounds():
    arena = dispatch.StackArena(max_bufs=2, max_bytes=1 << 20)
    b1 = arena.get(10_000)
    assert b1.cap >= 10_000 and b1.cap % 4096 == 0
    assert b1.flat.ctypes.data % 4096 == 0, "arena buffers are page-aligned"
    arena.release(b1, np.zeros(1, np.uint8))  # numpy out = consumed
    b2 = arena.get(9_000)
    assert b2 is b1, "same-bucket request must reuse the pooled buffer"
    arena.release(b2, None)
    # pool bound: a third distinct buffer over max_bufs is dropped
    b3, b4, b5 = arena.get(1 << 14), arena.get(1 << 14), arena.get(1 << 14)
    for b in (b3, b4, b5):
        arena.release(b, None)
    assert arena.stats()["pooled"] <= 2
    arena.close()
    assert arena.stats()["pooled"] == 0


def test_arena_quarantines_unready_outputs():
    class FakeLazy:
        """Mimics an in-flight jax array: is_ready flips when the
        'device' finishes."""

        def __init__(self):
            self.ready = False

        def is_ready(self):
            return self.ready

        def block_until_ready(self):
            self.ready = True

    arena = dispatch.StackArena(max_bufs=4, max_bytes=1 << 20)
    buf = arena.get(4096)
    lazy = FakeLazy()
    arena.release(buf, lazy)
    st = arena.stats()
    assert st["quarantined"] == 1 and st["pooled"] == 0, \
        "an unconsumed buffer must never re-enter the pool"
    fresh = arena.get(4096)
    assert fresh is not buf, "quarantined buffer handed out while in flight"
    arena.release(fresh, None)
    lazy.ready = True
    again = arena.get(4096)  # sweep reclaims the quarantined buffer now
    back = arena.get(4096)
    assert buf in (again, back), "consumed quarantined buffer never recycled"
    arena.close()


def test_consumed_probe_contract():
    assert dispatch._consumed(None)
    assert dispatch._consumed(np.zeros(3, np.uint8))
    import jax.numpy as jnp

    arr = jnp.zeros(8, jnp.uint8)
    arr.block_until_ready()
    assert dispatch._consumed(arr)


# -- golden safety: arena on/off, concurrent pipelines, all backends ---------


@pytest.mark.parametrize("backend", ["cpu", "tpu"])
def test_generate_ec_files_bit_identical_arena_on_off(tmp_path, monkeypatch,
                                                      backend):
    """The acceptance pin: .ec00-.ec13 bytes identical with the arena on
    and off, per backend (and the on/off pair hashes equal across
    backends by transitivity with the ISSUE-3 scheduler pins)."""
    monkeypatch.setenv("SWFS_EC_DISPATCH", "1")
    outs = {}
    for mode in ("0", "1"):
        monkeypatch.setenv("SWFS_EC_DISPATCH_ARENA", mode)
        base = str(tmp_path / f"a{backend}{mode}")
        _make_volume(base, seed=21)
        coder = new_coder(10, 4, backend)
        ec_files.generate_ec_files(base, coder, TEST_GEO, batch_size=50)
        outs[mode] = [
            open(TEST_GEO.shard_file_name(base, i), "rb").read()
            for i in range(14)
        ]
        dispatch.shutdown_all()
    for i in range(14):
        assert outs["0"][i] == outs["1"][i], f"shard {i} differs"


def test_concurrent_encode_reconstruct_recycled_arena_golden(monkeypatch):
    """Concurrent encode + reconstruct pipelines over ONE scheduler's
    recycled arena: every slab's bytes must match the direct per-slab
    oracle, and the arena must have provably recycled (hit > 0)."""
    monkeypatch.setenv("SWFS_EC_DISPATCH_ARENA", "1")
    coder = RSCodecCPU(10, 4)
    oracle = RSCodecCPU(10, 4)
    sched = dispatch.EcDispatchScheduler(coder, window=0.005)
    rng = np.random.default_rng(7)
    shards_pool = []
    for _ in range(4):
        data = rng.integers(0, 256, (10, 777), dtype=np.uint8)
        shards_pool.append(np.asarray(oracle.encode(
            np.vstack([data, np.zeros((4, 777), np.uint8)]))))
    pres = tuple(range(3, 14))  # 0..2 lost
    errs = []
    hits0 = _arena_count("hit")

    def encoder(tid):
        try:
            r = np.random.default_rng(100 + tid)
            for i in range(12):
                slab = r.integers(0, 256, (10, 64 + 8 * (i % 5)),
                                  dtype=np.uint8)
                fut = sched.encode_parity(slab)
                want = np.asarray(oracle.encode_parity(slab))
                got = np.asarray(fut)
                if not np.array_equal(got, want):
                    raise AssertionError(f"encode bytes diverged (t{tid}/{i})")
        except BaseException as e:
            errs.append(e)

    def reconstructor(tid):
        try:
            for i in range(12):
                shards = shards_pool[(tid + i) % len(shards_pool)]
                stk = np.stack([shards[p] for p in pres])
                fut = sched.reconstruct_stacked(pres, stk)
                missing, rows = fut.result(timeout=30)
                for j, mid in enumerate(missing):
                    if not np.array_equal(np.asarray(rows[j]), shards[mid]):
                        raise AssertionError(
                            f"reconstruct bytes diverged (t{tid}/{i})")
        except BaseException as e:
            errs.append(e)

    ths = [threading.Thread(target=encoder, args=(t,)) for t in range(3)] \
        + [threading.Thread(target=reconstructor, args=(t,))
           for t in range(2)]
    for t in ths:
        t.start()
    for t in ths:
        t.join()
    sched.close()
    assert not errs, errs[0]
    assert _arena_count("hit") > hits0, \
        "arena never recycled a buffer under concurrent pipelines"


def test_jax_backend_recycling_is_safe(monkeypatch):
    """The aliasing trap the quarantine exists for: jax's CPU client
    zero-copies page-aligned host buffers, so a recycled arena buffer
    could be the backing store of an in-flight device array. Repeated
    batches through the jax coder must stay bit-identical while buffers
    recycle."""
    monkeypatch.setenv("SWFS_EC_DISPATCH_ARENA", "1")
    coder = new_coder(10, 4, "tpu")
    oracle = RSCodecCPU(10, 4)
    sched = dispatch.EcDispatchScheduler(coder, window=30.0)
    rng = np.random.default_rng(9)
    try:
        for round_ in range(6):
            slabs = [rng.integers(0, 256, (10, 512), dtype=np.uint8)
                     for _ in range(6)]
            futs = [sched.encode_parity(s) for s in slabs]
            outs = [np.asarray(f) for f in futs]  # forces every result
            for s, got in zip(slabs, outs):
                assert np.array_equal(
                    got, np.asarray(oracle.encode_parity(s))), \
                    f"round {round_}: recycled arena corrupted a dispatch"
    finally:
        sched.close()


# -- steady-state allocation guard -------------------------------------------


def test_dispatch_hot_loop_allocations_steady_state(monkeypatch):
    """tracemalloc guard: after warmup the dispatch hot loop's packing
    allocates O(1) new blocks per batch with the arena on (misses stop;
    peak excludes the [V*k*B] staging buffer) vs O(V) off (a fresh
    V-proportional staging allocation every batch)."""
    coder = RSCodecCPU(10, 4)
    rng = np.random.default_rng(11)
    v, b = 16, 2048
    slabs = [rng.integers(0, 256, (10, b), dtype=np.uint8)
             for _ in range(v)]

    def run_batches(n, sched):
        for _ in range(n):
            futs = [sched.encode_parity(s) for s in slabs]
            futs[-1].result(timeout=30)  # demand flush batches the lane
            for f in futs:
                f.result(timeout=30)

    peaks = {}
    for mode in ("1", "0"):
        monkeypatch.setenv("SWFS_EC_DISPATCH_ARENA", mode)
        sched = dispatch.EcDispatchScheduler(coder, window=30.0)
        try:
            run_batches(3, sched)  # warmup: arena sizes its buckets
            miss0 = _arena_count("miss") + _arena_count("resize")
            tracemalloc.start()
            try:
                run_batches(1, sched)  # settle tracemalloc itself
                tracemalloc.reset_peak()
                base = tracemalloc.get_traced_memory()[0]
                run_batches(4, sched)
                peaks[mode] = tracemalloc.get_traced_memory()[1] - base
            finally:
                tracemalloc.stop()
            if mode == "1":
                assert _arena_count("miss") + _arena_count("resize") \
                    == miss0, "arena still allocating after warmup (not O(1))"
        finally:
            sched.close()
    staging = v * 10 * b  # the [k, V*B] wide buffer the arena recycles
    assert peaks["0"] - peaks["1"] > staging // 2, \
        (f"arena did not remove the per-batch staging allocation: "
         f"on={peaks['1']} off={peaks['0']} staging={staging}")


# -- zero-fill elision --------------------------------------------------------


def test_zero_fill_elided_and_ragged_tails_correct(monkeypatch):
    """Wide packing memsets nothing (every byte is payload) and ragged
    batches still produce exactly the per-slab oracle bytes."""
    monkeypatch.setenv("SWFS_EC_DISPATCH_ARENA", "1")
    coder = RSCodecCPU(10, 4)
    sched = dispatch.EcDispatchScheduler(coder, window=30.0)
    rng = np.random.default_rng(13)
    widths = [512, 100, 37, 512, 9]
    slabs = [rng.integers(0, 256, (10, w), dtype=np.uint8) for w in widths]
    z0 = int(stats.EC_DISPATCH_ZEROFILL_ELIDED.value())
    try:
        futs = [sched.encode_parity(s) for s in slabs]
        futs[-1].result(timeout=30)
        for s, f in zip(slabs, futs):
            assert np.array_equal(np.asarray(f),
                                  np.asarray(coder.encode_parity(s)))
    finally:
        sched.close()
    elided = int(stats.EC_DISPATCH_ZEROFILL_ELIDED.value()) - z0
    assert elided >= 10 * sum(widths), \
        "wide packing must elide the whole packed region's zero-fill"


# -- NUMA pinning plane -------------------------------------------------------


def test_numa_cpulist_parser():
    assert numa._parse_cpulist("0-3,8,10-11\n") == [0, 1, 2, 3, 8, 10, 11]
    assert numa._parse_cpulist("0\n") == [0]
    assert numa._parse_cpulist("") == []


def test_numa_topology_fallback_and_fake_sysfs(tmp_path):
    # absent sysfs tree -> one pseudo-node spanning the process CPUs
    nodes = numa.node_cpus(sys_root=str(tmp_path / "nope"))
    assert len(nodes) == 1 and nodes[0], nodes
    # fake two-node tree
    for i, lst in enumerate(("0-1", "2-3")):
        d = tmp_path / f"node{i}"
        d.mkdir()
        (d / "cpulist").write_text(lst + "\n")
    nodes = numa.node_cpus(sys_root=str(tmp_path))
    assert nodes == [[0, 1], [2, 3]]


def test_numa_pin_gate_off_is_noop(monkeypatch):
    monkeypatch.delenv("SWFS_EC_DISPATCH_PIN", raising=False)
    numa._reset_for_tests()
    assert numa.pin_thread() is None
    assert numa.pinning_stats()["threadsPinned"] == 0


def test_numa_pin_gate_on_pins_or_degrades(monkeypatch):
    monkeypatch.setenv("SWFS_EC_DISPATCH_PIN", "1")
    numa._reset_for_tests()
    before = None
    if hasattr(os, "sched_getaffinity"):
        before = os.sched_getaffinity(0)
    try:
        got = numa.pin_thread(node_hint=0)
        st = numa.pinning_stats()
        if got is None:
            assert st["noops"] >= 1  # degraded softly, never raised
        else:
            assert set(got) <= (before or set(got))
            assert st["threadsPinned"] == 1
    finally:
        if before is not None:
            os.sched_setaffinity(0, before)
        numa._reset_for_tests()


# -- scrub fadvise satellite --------------------------------------------------


def test_drop_page_cache_calls_fadvise(tmp_path, monkeypatch):
    if not hasattr(os, "posix_fadvise"):
        pytest.skip("no posix_fadvise on this platform")
    from seaweedfs_tpu.storage.backend import DiskFile, MmapFile

    p = tmp_path / "f.dat"
    p.write_bytes(b"x" * 8192)
    calls = []
    real = os.posix_fadvise

    def spy(fd, off, ln, advice):
        calls.append((off, ln, advice))
        return real(fd, off, ln, advice)

    monkeypatch.setattr(os, "posix_fadvise", spy)
    df = DiskFile(str(p))
    df.drop_page_cache(0, 4096)
    df.close()
    mf = MmapFile(str(p))
    mf.drop_page_cache()
    mf.close()
    assert calls == [(0, 4096, os.POSIX_FADV_DONTNEED),
                     (0, 0, os.POSIX_FADV_DONTNEED)]


def test_scrub_sweep_fadvises_swept_range(tmp_path, monkeypatch):
    """The paced CRC sweep must DONTNEED exactly the windows it read —
    and must not when SWFS_SCRUB_FADVISE=0."""
    if not hasattr(os, "posix_fadvise"):
        pytest.skip("no posix_fadvise on this platform")
    from seaweedfs_tpu.scrub import scrubber as scrub_mod

    class Backing:
        def __init__(self):
            self.calls = []

        def drop_page_cache(self, off, ln):
            self.calls.append((off, ln))

    b = Backing()
    monkeypatch.setenv("SWFS_SCRUB_FADVISE", "1")
    scrub_mod._drop_swept_range(b, 0, 1000)
    scrub_mod._drop_swept_range(b, 1000, 0)  # empty window: skipped
    monkeypatch.setenv("SWFS_SCRUB_FADVISE", "0")
    scrub_mod._drop_swept_range(b, 2000, 1000)
    assert b.calls == [(0, 1000)]


def test_scrub_volume_sweep_emits_fadvise(tmp_path, monkeypatch):
    """End to end: a real needle sweep over a real volume drops its
    swept .dat range from the page cache (and keeps zero findings)."""
    if not hasattr(os, "posix_fadvise"):
        pytest.skip("no posix_fadvise on this platform")
    from seaweedfs_tpu.scrub import Scrubber
    from seaweedfs_tpu.storage.needle import Needle
    from seaweedfs_tpu.storage.store import Store

    calls = []
    real = os.posix_fadvise

    def spy(fd, off, ln, advice):
        calls.append((off, ln, advice))
        return real(fd, off, ln, advice)

    monkeypatch.setenv("SWFS_SCRUB_FADVISE", "1")
    monkeypatch.setattr(os, "posix_fadvise", spy)
    st = Store([str(tmp_path)], coder=RSCodecCPU(10, 4))
    try:
        v = st.add_volume(1)
        rng = np.random.default_rng(5)
        for i in range(1, 11):
            v.write_needle(Needle.create(
                i, 0xABC, rng.integers(0, 256, 500, np.uint8).tobytes()))
        sc = Scrubber(st, None, interval_s=0, max_mbps=0)
        report = sc.run_once(anti_entropy=False)
        assert report.needles == 10
        assert report.findings == []
    finally:
        st.close()
    dontneed = [c for c in calls if c[2] == os.POSIX_FADV_DONTNEED]
    assert dontneed, "sweep finished without dropping its swept range"
