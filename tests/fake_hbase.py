"""In-process fake HBase Thrift2 gateway: THBaseService (get/put/
deleteSingle/getScannerResults) over the real Thrift strict binary
protocol. The protocol parser/encoder here is written independently of
seaweedfs_tpu's thrift_wire.py (same public spec, separate code), so a
framing bug in either side fails the tests instead of cancelling out.
Cells live in one table as {row: {family: value}} with the single 'a'
qualifier the store uses; unknown methods answer a TApplicationException
like a real gateway.
"""

from __future__ import annotations

import socket
import struct
import threading

VERSION_1 = 0x80010000
CALL, REPLY, EXCEPTION = 1, 2, 3
BOOL, BYTE, DOUBLE = 2, 3, 4
I16, I32, I64 = 6, 8, 10
STRING, STRUCT, MAP, SET, LIST = 11, 12, 13, 14, 15


class _Dec:
    def __init__(self, f):
        self.f = f

    def take(self, n: int) -> bytes:
        b = self.f.read(n)
        if len(b) != n:
            raise EOFError
        return b

    def value(self, t: int):
        if t == BOOL:
            return self.take(1) != b"\x00"
        if t == BYTE:
            return struct.unpack(">b", self.take(1))[0]
        if t == DOUBLE:
            return struct.unpack(">d", self.take(8))[0]
        if t == I16:
            return struct.unpack(">h", self.take(2))[0]
        if t == I32:
            return struct.unpack(">i", self.take(4))[0]
        if t == I64:
            return struct.unpack(">q", self.take(8))[0]
        if t == STRING:
            return self.take(struct.unpack(">i", self.take(4))[0])
        if t == STRUCT:
            return self.struct()
        if t in (LIST, SET):
            et = struct.unpack(">b", self.take(1))[0]
            n = struct.unpack(">i", self.take(4))[0]
            return [self.value(et) for _ in range(n)]
        if t == MAP:
            kt, vt = struct.unpack(">bb", self.take(2))
            n = struct.unpack(">i", self.take(4))[0]
            return {self.value(kt): self.value(vt) for _ in range(n)}
        raise ValueError(f"type {t}")

    def struct(self) -> dict:
        out = {}
        while True:
            t = struct.unpack(">b", self.take(1))[0]
            if t == 0:
                return out
            fid = struct.unpack(">h", self.take(2))[0]
            out[fid] = self.value(t)


def _e_str(b: bytes) -> bytes:
    return struct.pack(">i", len(b)) + b


def _e_field(fid: int, t: int, payload: bytes) -> bytes:
    return struct.pack(">bh", t, fid) + payload


def _e_struct(*fields: bytes) -> bytes:
    return b"".join(fields) + b"\x00"


def _e_list(etype: int, elems: list[bytes]) -> bytes:
    return struct.pack(">bi", etype, len(elems)) + b"".join(elems)


def _tresult(row: bytes, family: bytes, value: bytes) -> bytes:
    cv = _e_struct(_e_field(1, STRING, _e_str(family)),
                   _e_field(2, STRING, _e_str(b"a")),
                   _e_field(3, STRING, _e_str(value)))
    return _e_struct(_e_field(1, STRING, _e_str(row)),
                     _e_field(2, LIST, _e_list(STRUCT, [cv])))


class FakeHbaseThriftServer:
    def __init__(self, *, tables: tuple[str, ...] = ("seaweedfs",)):
        # {table: {row: {family: value}}}
        self.tables: dict[bytes, dict[bytes, dict[bytes, bytes]]] = {
            t.encode(): {} for t in tables}
        self.lock = threading.Lock()
        self.calls: list[str] = []  # observed method names, for tests
        self._listen = socket.socket()
        self._listen.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listen.bind(("localhost", 0))
        self._listen.listen(16)
        self.port = self._listen.getsockname()[1]
        self._stop = threading.Event()
        threading.Thread(target=self._accept, daemon=True).start()

    def stop(self) -> None:
        self._stop.set()
        try:
            self._listen.close()
        except OSError:
            pass

    def _accept(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._listen.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn: socket.socket) -> None:
        f = conn.makefile("rb")
        try:
            while not self._stop.is_set():
                try:
                    d = _Dec(f)
                    head = struct.unpack(">i", d.take(4))[0] & 0xFFFFFFFF
                    if head & 0xFFFF0000 != VERSION_1:
                        return  # not strict binary protocol: hang up
                    name = d.take(struct.unpack(">i", d.take(4))[0])
                    seq = struct.unpack(">i", d.take(4))[0]
                    args = d.struct()
                except EOFError:
                    return
                self.calls.append(name.decode())
                body, mtype = self._dispatch(name.decode(), args)
                head = struct.pack(">i",
                                   (VERSION_1 | mtype) - (1 << 32))
                conn.sendall(head + _e_str(name)
                             + struct.pack(">i", seq) + body)
        except OSError:
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    # -- THBaseService ------------------------------------------------------

    def _table(self, args: dict):
        t = self.tables.get(args.get(1, b""))
        if t is None:
            # declared TIOError {1: message} in reply field 1
            return None, _e_struct(_e_field(1, STRUCT, _e_struct(
                _e_field(1, STRING,
                         _e_str(b"TableNotFoundException")))))
        return t, None

    @staticmethod
    def _families(spec: dict, field_id: int = 2,
                  default: bytes = b"meta") -> list[bytes]:
        # TGet/TDelete carry columns in field 2; TScan in field 3
        # (field 2 is stopRow) — hbase.thrift
        cols = spec.get(field_id)
        if not cols:
            return [default]
        return [c.get(1, default) for c in cols]

    def _dispatch(self, method: str, args: dict) -> tuple[bytes, int]:
        with self.lock:
            if method in ("get", "exists"):
                table, err = self._table(args)
                if err is not None:
                    return err, REPLY
                tget = args.get(2, {})
                row = tget.get(1, b"")
                fams = self._families(tget)
                cells = table.get(row, {})
                hit = next((fam for fam in fams if fam in cells), None)
                if method == "exists":
                    return _e_struct(_e_field(
                        0, BOOL, b"\x01" if hit else b"\x00")), REPLY
                if hit is None:
                    return _e_struct(_e_field(0, STRUCT,
                                              _e_struct())), REPLY
                return _e_struct(_e_field(0, STRUCT, _tresult(
                    row, hit, cells[hit]))), REPLY
            if method == "put":
                table, err = self._table(args)
                if err is not None:
                    return err, REPLY
                tput = args.get(2, {})
                row = tput.get(1, b"")
                for cv in tput.get(2) or []:
                    fam, qual, val = cv.get(1), cv.get(2), cv.get(3)
                    assert qual == b"a", f"unexpected qualifier {qual!r}"
                    table.setdefault(row, {})[fam] = val
                return _e_struct(), REPLY
            if method == "deleteSingle":
                table, err = self._table(args)
                if err is not None:
                    return err, REPLY
                tdel = args.get(2, {})
                row = tdel.get(1, b"")
                cells = table.get(row)
                if cells is not None:
                    for fam in self._families(tdel):
                        cells.pop(fam, None)
                    if not cells:
                        table.pop(row, None)
                return _e_struct(), REPLY
            if method == "getScannerResults":
                table, err = self._table(args)
                if err is not None:
                    return err, REPLY
                tscan = args.get(2, {})
                start = tscan.get(1, b"")
                stop = tscan.get(2, b"")
                fams = self._families(tscan, field_id=3)
                n = args.get(3, 1024)
                rows = sorted(r for r in table
                              if r >= start and (not stop or r < stop))
                out = []
                for r in rows:
                    for fam in fams:
                        if fam in table[r]:
                            out.append(_tresult(r, fam, table[r][fam]))
                            break
                    if len(out) >= n:
                        break
                return _e_struct(_e_field(0, LIST,
                                          _e_list(STRUCT, out))), REPLY
            # TApplicationException {1: message, 2: type=1 unknown method}
            body = _e_struct(
                _e_field(1, STRING,
                         _e_str(f"unknown method {method}".encode())),
                _e_field(2, I32, struct.pack(">i", 1)))
            return body, EXCEPTION
