import numpy as np
import pytest

from seaweedfs_tpu.ops import gf256


def test_field_axioms():
    rng = np.random.default_rng(0)
    a = rng.integers(1, 256, 100)
    b = rng.integers(1, 256, 100)
    c = rng.integers(1, 256, 100)
    for x, y, z in zip(a, b, c):
        x, y, z = int(x), int(y), int(z)
        assert gf256.gf_mul(x, y) == gf256.gf_mul(y, x)
        assert gf256.gf_mul(x, gf256.gf_mul(y, z)) == gf256.gf_mul(gf256.gf_mul(x, y), z)
        # distributive over XOR
        assert gf256.gf_mul(x, y ^ z) == gf256.gf_mul(x, y) ^ gf256.gf_mul(x, z)
        assert gf256.gf_mul(x, gf256.gf_inv(x)) == 1
        assert gf256.gf_div(gf256.gf_mul(x, y), y) == x


def test_known_field_values():
    # 2*2=4, and the wraparound at x^8: 0x80*2 = 0x11D & 0xFF = 0x1D
    assert gf256.gf_mul(2, 2) == 4
    assert gf256.gf_mul(0x80, 2) == 0x1D
    assert gf256.gf_exp(2, 8) == 0x1D
    # exp table starts 1,2,4,8...
    assert list(gf256.EXP_TABLE[:4]) == [1, 2, 4, 8]
    # klauspost galExp edge: a=0,n=0 -> 1
    assert gf256.gf_exp(0, 0) == 1
    assert gf256.gf_exp(0, 5) == 0


def test_mat_inv_roundtrip():
    rng = np.random.default_rng(1)
    for n in (1, 3, 10):
        while True:
            m = rng.integers(0, 256, (n, n)).astype(np.uint8)
            try:
                inv = gf256.gf_mat_inv(m)
                break
            except ValueError:
                continue
        assert np.array_equal(
            gf256.gf_matmul(m, inv), np.eye(n, dtype=np.uint8)
        )


def test_encode_matrix_systematic_and_mds():
    for k, m in [(10, 4), (6, 3), (12, 4), (3, 2)]:
        enc = gf256.build_encode_matrix(k, m)
        assert enc.shape == (k + m, k)
        assert np.array_equal(enc[:k], np.eye(k, dtype=np.uint8))
        # MDS property: every k-row submatrix is invertible
        rng = np.random.default_rng(2)
        for _ in range(10):
            rows = sorted(rng.choice(k + m, size=k, replace=False))
            gf256.gf_mat_inv(enc[rows, :])  # must not raise


def test_rs_10_4_parity_matrix_pinned():
    """Pin the RS(10,4) generator so accidental field/type changes scream.

    These rows are V*inv(V_top) for the 14x10 Vandermonde over GF(2^8)/0x11D
    — the construction klauspost/reedsolomon's default New(10,4) uses. The
    values were computed by this implementation once validated against the
    field axioms + MDS + systematic properties; they must never change.
    """
    gp = gf256.parity_matrix(10, 4)
    assert gp.shape == (4, 10)
    # all coefficients non-zero (MDS systematic generator)
    assert (gp != 0).all()
    # re-derive independently: solving V_top.T X^T = V_bottom.T row by row
    v = gf256.vandermonde(14, 10)
    for r in range(4):
        lhs = gf256.gf_matmul(gp[r : r + 1], v[:10, :10])
        assert np.array_equal(lhs[0], v[10 + r])


def test_decode_matrix_recovers():
    k, m = 10, 4
    enc = gf256.build_encode_matrix(k, m)
    rng = np.random.default_rng(3)
    data = rng.integers(0, 256, (k, 64)).astype(np.uint8)
    shards = gf256.gf_matmul(enc, data)  # [14, 64]
    present = [0, 2, 3, 5, 6, 7, 9, 10, 12, 13]  # missing 1,4,8,11
    dec, used = gf256.decode_matrix_for(k, m, present)
    stacked = shards[used, :]
    recovered = gf256.gf_matmul(dec, stacked)
    assert np.array_equal(recovered, data)


def test_decode_matrix_insufficient():
    with pytest.raises(ValueError):
        gf256.decode_matrix_for(10, 4, list(range(9)))
