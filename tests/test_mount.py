"""Mount layer tests: inode map, page writer, and WFS over a live
in-process cluster (SURVEY.md §2.6 FUSE mount, §3.6 FUSE write path)."""

import errno
import socket
import time

import numpy as np
import pytest

from seaweedfs_tpu.mount import (
    ROOT_INODE,
    WFS,
    FuseError,
    InodeToPath,
    MemChunk,
    UploadPipeline,
)
from seaweedfs_tpu.pb import rpc
from seaweedfs_tpu.server.filer import FilerServer
from seaweedfs_tpu.server.master import MasterServer
from seaweedfs_tpu.server.volume import VolumeServer


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


# -- inode map -------------------------------------------------------------

def test_inode_to_path_lookup_forget():
    m = InodeToPath()
    a = m.lookup("/a", True)
    b = m.lookup("/a/b")
    assert a != b and a != ROOT_INODE
    assert m.get_path(b) == "/a/b"
    assert m.lookup("/a/b") == b  # second lookup bumps refcount
    m.forget(b)  # one ref remains
    assert m.get_path(b) == "/a/b"
    m.forget(b)
    with pytest.raises(KeyError):
        m.get_path(b)


def test_inode_to_path_rename_moves_subtree():
    m = InodeToPath()
    d = m.lookup("/d", True)
    f = m.lookup("/d/f")
    m.move_path("/d", "/e")
    assert m.get_path(d) == "/e"
    assert m.get_path(f) == "/e/f"
    assert m.get_inode("/d/f") is None


def test_inode_hard_links_share_inode():
    m = InodeToPath()
    f = m.lookup("/f")
    m.add_path(f, "/g")
    assert m.get_inode("/g") == f
    m.remove_path("/f")
    assert m.get_path(f) == "/g"  # inode survives while a link remains


# -- page writer -----------------------------------------------------------

def test_mem_chunk_intervals_and_read():
    c = MemChunk(0, 100)
    c.write(b"a" * 10, 0, 1)
    c.write(b"b" * 10, 20, 2)
    assert c.continuous_intervals() == [(0, 10), (20, 30)]
    assert not c.is_complete()
    c.write(b"c" * 100, 0, 3)
    assert c.is_complete()
    buf = memoryview(bytearray(5))
    c.read_at(buf, 18)
    assert bytes(buf) == b"ccccc"


def test_upload_pipeline_seal_flush_and_read_back():
    import threading

    saved = []
    gate = threading.Event()  # hold uploads until the dirty read below ran

    def save(data, offset, ts):
        gate.wait(10)
        saved.append((offset, data))

    p = UploadPipeline(16, save, concurrency=2)
    p.save_data_at(b"x" * 16, 0, 1)     # full chunk -> sealed immediately
    p.save_data_at(b"y" * 5, 16, 2)     # partial, flushed later
    p.save_data_at(b"z" * 3, 30, 3)     # second interval in chunk 1
    buf = memoryview(bytearray(8))
    covered = p.maybe_read_data_at(buf, 14)
    assert covered and covered[0] == (0, 7)
    assert bytes(buf[:7]) == b"xxyyyyy"
    gate.set()
    p.flush()
    # the 3-byte write at 30 straddles the chunk-1/chunk-2 boundary
    assert sorted(saved) == [(0, b"x" * 16), (16, b"y" * 5),
                             (30, b"zz"), (32, b"z")]
    p.close()


def test_upload_pipeline_overlapping_writes_latest_wins():
    saved = {}

    def save(data, offset, ts):
        saved[offset] = data

    p = UploadPipeline(64, save, concurrency=1)
    p.save_data_at(b"a" * 10, 0, 1)
    p.save_data_at(b"B" * 4, 3, 2)
    p.flush()
    assert saved[0] == b"aaaBBBBaaa"
    p.close()


def test_upload_pipeline_spills_to_swapfile(tmp_path):
    """Writing faster than uploads drain must spill past the memory budget
    (page_chunk_swapfile.go): bytes stay correct, reads-before-flush serve
    from the swap file, slots recycle."""
    import threading

    gate = threading.Event()
    saved = {}

    def slow_save(data, offset, ts):
        gate.wait(10)  # hold uploads so sealed chunks pile up
        saved[offset] = data

    chunk = 1 << 10
    p = UploadPipeline(chunk, slow_save, concurrency=2,
                       memory_chunk_limit=2, swap_dir=str(tmp_path))
    blobs = {}
    for i in range(12):  # 12 chunks against a 2-chunk memory budget
        blob = bytes([65 + i]) * chunk
        blobs[i * chunk] = blob
        p.save_data_at(blob, i * chunk, i + 1)
    assert p.swapped_out >= 10, p.swapped_out

    # read-your-writes straight out of the swap file
    buf = memoryview(bytearray(chunk))
    covered = p.maybe_read_data_at(buf, 5 * chunk)
    assert covered == [(0, chunk)]
    assert bytes(buf) == blobs[5 * chunk]

    gate.set()
    p.flush()
    assert saved == blobs
    # slots are recycled once uploads complete
    assert p._swap is not None and len(p._swap._free) > 0
    p.close()


def test_upload_pipeline_partial_chunks_spill(tmp_path):
    """Partial (non-contiguous) writes in spilled chunks keep interval
    bookkeeping intact through flush."""
    saved = {}
    p = UploadPipeline(100, lambda d, o, t: saved.__setitem__(o, d),
                       concurrency=1, memory_chunk_limit=1,
                       swap_dir=str(tmp_path))
    p.save_data_at(b"m" * 100, 0, 1)      # fills chunk 0 (mem, sealed)
    p.save_data_at(b"a" * 10, 100, 2)     # chunk 1 partial
    p.save_data_at(b"b" * 10, 150, 3)     # chunk 1, disjoint interval
    p.save_data_at(b"c" * 7, 260, 4)      # chunk 2 partial
    p.flush()
    assert saved[0] == b"m" * 100
    assert saved[100] == b"a" * 10 and saved[150] == b"b" * 10
    assert saved[260] == b"c" * 7
    p.close()


def test_mem_budget_shared_across_pipelines(tmp_path):
    """One mount-wide budget: a second handle's chunks spill once other
    handles hold the memory (not a per-handle 64MB each)."""
    from seaweedfs_tpu.mount.page_writer import MemBudget

    budget = MemBudget(2)
    saved = {}

    def save(d, o, t):
        saved[o] = d

    p1 = UploadPipeline(100, save, concurrency=1, budget=budget,
                        swap_dir=str(tmp_path))
    p2 = UploadPipeline(100, save, concurrency=1, budget=budget,
                        swap_dir=str(tmp_path))
    p1.save_data_at(b"a" * 10, 0, 1)      # mem (partial: stays writable)
    p1.save_data_at(b"b" * 10, 100, 2)    # mem — budget now exhausted
    p2.save_data_at(b"c" * 10, 0, 3)      # must spill
    assert p2.swapped_out == 1
    p1.flush()
    p2.flush()
    assert set(saved) == {0, 100}  # both pipelines uploaded (0 twice)
    p1.close()
    p2.close()
    # budget fully returned after close
    assert budget.try_take() and budget.try_take()


# -- live cluster ----------------------------------------------------------

@pytest.fixture(scope="module")
def wfs(tmp_path_factory):
    mport = _free_port()
    master = MasterServer(ip="localhost", port=mport, volume_size_limit_mb=64)
    master.start(vacuum_interval=3600)
    vsrv = VolumeServer(
        directories=[str(tmp_path_factory.mktemp("vol"))],
        master=f"localhost:{mport}", ip="localhost", port=_free_port(),
        pulse_seconds=1)
    vsrv.start()
    fsrv = FilerServer(ip="localhost", port=_free_port(),
                       master=f"localhost:{mport}",
                       store_dir=str(tmp_path_factory.mktemp("filer")),
                       chunk_size=64 * 1024)
    fsrv.start()
    deadline = time.time() + 10
    while time.time() < deadline and not master.topo.nodes:
        time.sleep(0.05)
    w = WFS(rpc.grpc_address(fsrv.address), chunk_size=32 * 1024)
    yield w
    w.close()
    fsrv.stop()
    vsrv.stop()
    master.stop()
    rpc.reset_channels()


def test_wfs_mkdir_create_write_read(wfs):
    dino, _ = wfs.mkdir(ROOT_INODE, "docs")
    ino, entry, fh = wfs.create(dino, "hello.txt", 0o644)
    wfs.write(fh, 0, b"hello ")
    wfs.write(fh, 6, b"world")
    assert wfs.read(fh, 0, 100) == b"hello world"  # read-your-writes
    wfs.flush(fh)
    wfs.release(fh)
    # fresh handle reads from volume servers through the chunk cache
    fh2 = wfs.open(ino)
    assert wfs.read(fh2, 0, 100) == b"hello world"
    assert wfs.read(fh2, 6, 5) == b"world"
    wfs.release(fh2)
    e = wfs.getattr(ino)
    assert e.size() == 11


def test_wfs_multi_chunk_file(wfs):
    rng = np.random.default_rng(0)
    payload = rng.integers(0, 256, size=100_000, dtype=np.uint8).tobytes()
    dino, _ = wfs.mkdir(ROOT_INODE, "big")
    ino, _, fh = wfs.create(dino, "blob", 0o644)
    # write out of order in odd-sized pieces (chunk_size is 32k)
    for off in range(0, len(payload), 7001):
        wfs.write(fh, off, payload[off:off + 7001])
    wfs.flush(fh)
    wfs.release(fh)
    fh = wfs.open(ino)
    assert wfs.read(fh, 0, len(payload)) == payload
    assert wfs.read(fh, 40_000, 1000) == payload[40_000:41_000]
    wfs.release(fh)


def test_wfs_write_past_memory_budget_spills(wfs, tmp_path):
    """End-to-end: a file larger than the pipeline's memory budget goes
    through the swap file and still reads back byte-identical."""
    from seaweedfs_tpu.mount.page_writer import MemBudget

    saved_budget = wfs.mem_budget
    wfs.mem_budget = MemBudget(2)  # 2 x 32KB mount-wide budget
    try:
        rng = np.random.default_rng(42)
        payload = rng.integers(0, 256, size=10 * 32 * 1024,
                               dtype=np.uint8).tobytes()
        dino, _ = wfs.mkdir(ROOT_INODE, "spill")
        ino, _, fh = wfs.create(dino, "big.bin", 0o644)
        chunk = 32 * 1024
        # touch every chunk first so 10 partial chunks coexist (8 must
        # spill), then fill them
        for i in range(10):
            wfs.write(fh, i * chunk, payload[i * chunk:i * chunk + 1])
        h = wfs._handle(fh)
        assert h.pages.swapped_out >= 8, h.pages.swapped_out
        for i in reversed(range(10)):
            wfs.write(fh, i * chunk, payload[i * chunk:(i + 1) * chunk])
        # dirty reads hit the swap-backed pages
        assert wfs.read(fh, 3 * chunk, 100) == payload[3 * chunk:3 * chunk + 100]
        wfs.flush(fh)
        wfs.release(fh)
        fh2 = wfs.open(ino)
        assert wfs.read(fh2, 0, len(payload)) == payload
        wfs.release(fh2)
    finally:
        wfs.mem_budget = saved_budget


def test_wfs_readdir_rename_unlink(wfs):
    dino, _ = wfs.mkdir(ROOT_INODE, "work")
    for name in ("a", "b", "c"):
        _, _, fh = wfs.create(dino, name)
        wfs.write(fh, 0, name.encode())
        wfs.flush(fh)
        wfs.release(fh)
    names = sorted(e.name for e in wfs.readdir(dino))
    assert names == ["a", "b", "c"]
    wfs.rename(dino, "a", dino, "a2")
    names = sorted(e.name for e in wfs.readdir(dino))
    assert names == ["a2", "b", "c"]
    ino = wfs.path_inode("/work/a2")
    fh = wfs.open(ino)
    assert wfs.read(fh, 0, 10) == b"a"
    wfs.release(fh)
    wfs.unlink(dino, "b")
    with pytest.raises(FuseError):
        wfs.lookup(dino, "b")


def test_wfs_truncate(wfs):
    dino, _ = wfs.mkdir(ROOT_INODE, "trunc")
    ino, _, fh = wfs.create(dino, "f")
    wfs.write(fh, 0, b"0123456789")
    wfs.flush(fh)
    wfs.release(fh)
    wfs.setattr(ino, size=4)
    fh = wfs.open(ino)
    assert wfs.read(fh, 0, 10) == b"0123"
    wfs.release(fh)


def test_wfs_symlink_xattr(wfs):
    dino, _ = wfs.mkdir(ROOT_INODE, "meta")
    ino, _ = wfs.symlink(dino, "lnk", "/meta/target")
    assert wfs.readlink(ino) == "/meta/target"
    fino, _, fh = wfs.create(dino, "file")
    wfs.flush(fh)
    wfs.release(fh)
    wfs.setxattr(fino, "user.tag", b"v1")
    assert wfs.getxattr(fino, "user.tag") == b"v1"
    assert wfs.listxattr(fino) == ["user.tag"]
    wfs.removexattr(fino, "user.tag")
    with pytest.raises(FuseError):
        wfs.getxattr(fino, "user.tag")


def test_wfs_hard_link(wfs):
    dino, _ = wfs.mkdir(ROOT_INODE, "links")
    ino, _, fh = wfs.create(dino, "orig")
    wfs.write(fh, 0, b"payload")
    wfs.flush(fh)
    wfs.release(fh)
    lino, linked = wfs.link(ino, dino, "alias")
    assert lino == ino
    fh = wfs.open(wfs.path_inode("/links/alias"))
    assert wfs.read(fh, 0, 10) == b"payload"
    wfs.release(fh)


def test_wfs_rmdir_nonempty_fails(wfs):
    dino, _ = wfs.mkdir(ROOT_INODE, "full")
    _, _, fh = wfs.create(dino, "kid")
    wfs.flush(fh)
    wfs.release(fh)
    with pytest.raises(FuseError):  # POSIX: ENOTEMPTY, never recursive
        wfs.rmdir(ROOT_INODE, "full")
    assert wfs.path_inode("/full/kid")  # child survived


def test_wfs_rename_with_open_handle(wfs):
    dino, _ = wfs.mkdir(ROOT_INODE, "rn")
    ino, _, fh = wfs.create(dino, "before")
    wfs.write(fh, 0, b"first")
    wfs.rename(dino, "before", dino, "after")
    wfs.write(fh, 5, b"+more")  # written after the rename
    wfs.flush(fh)
    wfs.release(fh)
    fh2 = wfs.open(wfs.path_inode("/rn/after"))
    assert wfs.read(fh2, 0, 20) == b"first+more"
    wfs.release(fh2)


def test_wfs_getattr_includes_dirty_size(wfs):
    dino, _ = wfs.mkdir(ROOT_INODE, "dirty")
    ino, _, fh = wfs.create(dino, "f")
    wfs.write(fh, 0, b"x" * 1000)  # buffered, not yet uploaded
    e = wfs.getattr(ino)
    assert wfs.entry_size(ino, e) == 1000
    wfs.flush(fh)
    wfs.release(fh)


def test_wfs_statfs(wfs):
    st = wfs.statfs()
    assert st["total"] >= 0


def test_pipeline_releases_completed_chunk_refs(tmp_path):
    """Completed uploads must not pin their MemChunk buffers until flush
    (unbounded RSS on long streaming writes): the next seal prunes them."""
    import gc
    import threading
    import weakref

    gate = threading.Event()
    p = UploadPipeline(64, lambda d, o, t: gate.wait(10), concurrency=2)
    p.save_data_at(b"x" * 64, 0, 1)  # seals chunk 0; upload blocked on gate
    with p._lock:
        ref = weakref.ref(next(iter(p._sealed.values())))
    assert ref() is not None
    gate.set()
    deadline = time.time() + 5
    while time.time() < deadline and p._sealed:
        time.sleep(0.01)  # upload drains without any flush()
    p.save_data_at(b"y" * 64, 64, 2)  # next seal prunes finished futures
    deadline = time.time() + 5
    while time.time() < deadline and ref() is not None:
        gc.collect()
        time.sleep(0.05)
    assert ref() is None, "completed chunk still pinned by _futures"
    p.flush()
    p.close()


@pytest.mark.skipif(
    not __import__("os").path.exists("/dev/fuse"),
    reason="no /dev/fuse in this environment")
def test_kernel_fuse_mount(wfs, tmp_path):
    """Real kernel mount through the bundled libfuse shim: plain os calls
    against the mountpoint exercise WFS end-to-end (mount_std.go parity)."""
    import os
    import threading
    import time as _time

    from seaweedfs_tpu.mount import fuse_binding

    if not fuse_binding.fuse_available():
        pytest.skip("fuse backend unavailable")
    mnt = str(tmp_path / "mnt")
    os.makedirs(mnt)
    t = threading.Thread(target=fuse_binding.mount, args=(wfs, mnt),
                         daemon=True)
    t.start()
    deadline = _time.time() + 15
    while _time.time() < deadline and not os.path.ismount(mnt):
        _time.sleep(0.1)
    assert os.path.ismount(mnt), "kernel mount did not appear"
    try:
        try:
            os.makedirs(f"{mnt}/kd")
        except OSError as e:
            if e.errno == errno.ENOSYS:
                # /dev/fuse exists and the mount "appears", but the
                # sandboxed kernel refuses actual FUSE ops
                pytest.skip("kernel FUSE ops unimplemented here")
            raise
        payload = b"fuse-bytes" * 2000
        with open(f"{mnt}/kd/a.bin", "wb") as f:
            f.write(payload)
        assert os.stat(f"{mnt}/kd/a.bin").st_size == len(payload)
        with open(f"{mnt}/kd/a.bin", "rb") as f:
            assert f.read() == payload
        os.rename(f"{mnt}/kd/a.bin", f"{mnt}/kd/b.bin")
        os.symlink("b.bin", f"{mnt}/kd/l")
        with open(f"{mnt}/kd/l", "rb") as f:
            assert f.read() == payload
        assert sorted(os.listdir(f"{mnt}/kd")) == ["b.bin", "l"]
        os.remove(f"{mnt}/kd/l")
        os.remove(f"{mnt}/kd/b.bin")
        os.rmdir(f"{mnt}/kd")
    finally:
        fuse_binding.unmount(mnt)
        t.join(timeout=10)


@pytest.mark.skipif(
    not __import__("os").path.exists("/dev/fuse"),
    reason="no /dev/fuse in this environment")
def test_weed_mount_cli_subprocess(tmp_path):
    """`weed mount` as a real subprocess: the CLI wires WFS + the fuse
    binding; the test does plain file IO against the mountpoint."""
    import os
    import signal
    import subprocess
    import sys
    import time as _time

    from seaweedfs_tpu.mount import fuse_binding

    if not fuse_binding.fuse_available():
        pytest.skip("fuse backend unavailable")
    mport = _free_port()
    master = MasterServer(ip="localhost", port=mport,
                          volume_size_limit_mb=64)
    master.start(vacuum_interval=3600)
    vsrv = VolumeServer(directories=[str(tmp_path / "cv")],
                        master=f"localhost:{mport}", ip="localhost",
                        port=_free_port(), pulse_seconds=1)
    vsrv.start()
    fsrv = FilerServer(ip="localhost", port=_free_port(),
                       master=f"localhost:{mport}",
                       store_dir=str(tmp_path / "cf"))
    fsrv.start()
    deadline = time.time() + 10
    while time.time() < deadline and not master.topo.nodes:
        time.sleep(0.05)
    filer_addr = fsrv.address
    mnt = str(tmp_path / "climnt")
    os.makedirs(mnt)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, "-m", "seaweedfs_tpu", "mount",
         "-filer", filer_addr, "-dir", mnt],
        env=env, stdout=subprocess.DEVNULL,
        stderr=open(str(tmp_path / "mount.log"), "w"))
    try:
        deadline = _time.time() + 30
        while _time.time() < deadline and not os.path.ismount(mnt):
            if proc.poll() is not None:  # crashed at startup: fail fast
                break
            _time.sleep(0.2)
        assert os.path.ismount(mnt), (
            f"CLI mount did not appear (rc={proc.poll()}): "
            + open(str(tmp_path / "mount.log")).read()[-500:])
        try:
            fh = open(f"{mnt}/cli.txt", "wb")
        except OSError as e:
            if e.errno == errno.ENOSYS:
                pytest.skip("kernel FUSE ops unimplemented here")
            raise
        with fh as f:
            f.write(b"via the weed mount subcommand")
        with open(f"{mnt}/cli.txt", "rb") as f:
            assert f.read() == b"via the weed mount subcommand"
        os.remove(f"{mnt}/cli.txt")
    finally:
        fuse_binding.unmount(mnt)
        proc.send_signal(signal.SIGINT)
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=5)
        fsrv.stop()
        vsrv.stop()
        master.stop()
        rpc.reset_channels()
