"""Raft tests: consensus core over an in-process transport, then a live
3-master cluster with leader failover (SURVEY.md §2.4 Raft row)."""

import socket
import time

import pytest
import requests

from seaweedfs_tpu.master.raft import (
    LEADER,
    LocalTransport,
    NotLeader,
    RaftNode,
)
from seaweedfs_tpu.pb import rpc
from seaweedfs_tpu.server.master import MasterServer
from seaweedfs_tpu.server.volume import VolumeServer


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def _mk_cluster_nodes(n=3, state_dir=None):
    transport = LocalTransport()
    ids = [f"node{i}" for i in range(n)]
    applied = {i: [] for i in ids}
    nodes = []
    for i in ids:
        node = RaftNode(
            i, list(ids), applied[i].append, transport=transport,
            state_dir=state_dir)
        transport.register(node)
        nodes.append(node)
    return transport, nodes, applied


def _wait_leader(nodes, timeout=10.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        leaders = [n for n in nodes if n.role == LEADER]
        if len(leaders) == 1:
            return leaders[0]
        time.sleep(0.05)
    raise AssertionError(f"no single leader: "
                         f"{[(n.node_id, n.role) for n in nodes]}")


def test_raft_elects_single_leader_and_replicates():
    transport, nodes, applied = _mk_cluster_nodes()
    for n in nodes:
        n.start()
    try:
        leader = _wait_leader(nodes)
        for v in range(1, 6):
            leader.propose({"op": "max_volume_id", "value": v})
        deadline = time.time() + 5
        while time.time() < deadline and not all(
                len(applied[n.node_id]) == 5 for n in nodes):
            time.sleep(0.05)
        for n in nodes:
            assert [c["value"] for c in applied[n.node_id]] == [1, 2, 3, 4, 5]
        follower = next(n for n in nodes if n.role != LEADER)
        with pytest.raises(NotLeader):
            follower.propose({"op": "x"})
    finally:
        for n in nodes:
            n.stop()


def test_raft_leader_failover_and_log_consistency():
    transport, nodes, applied = _mk_cluster_nodes()
    for n in nodes:
        n.start()
    try:
        leader = _wait_leader(nodes)
        leader.propose({"v": 1})
        # partition the leader away; remaining two elect a new one
        transport.partitioned.add(leader.node_id)
        survivors = [n for n in nodes if n is not leader]
        new_leader = _wait_leader(survivors)
        assert new_leader is not leader
        new_leader.propose({"v": 2})
        # heal the partition: old leader steps down and catches up
        transport.partitioned.clear()
        deadline = time.time() + 5
        while time.time() < deadline and (
                leader.role == LEADER or
                len(applied[leader.node_id]) < 2):
            time.sleep(0.05)
        assert leader.role != LEADER
        assert [c.get("v") for c in applied[leader.node_id]] == [1, 2]
    finally:
        for n in nodes:
            n.stop()


def test_raft_minority_cannot_commit():
    transport, nodes, applied = _mk_cluster_nodes()
    for n in nodes:
        n.start()
    try:
        leader = _wait_leader(nodes)
        # cut BOTH followers: leader keeps role but cannot commit
        for n in nodes:
            if n is not leader:
                transport.partitioned.add(n.node_id)
        with pytest.raises(TimeoutError):
            leader.propose({"v": 99}, timeout=1.0)
        assert all(len(applied[n.node_id]) == 0 for n in nodes)
    finally:
        for n in nodes:
            n.stop()


def test_raft_persistence_and_restart(tmp_path):
    transport, nodes, applied = _mk_cluster_nodes(
        state_dir=str(tmp_path))
    for n in nodes:
        n.start()
    leader = _wait_leader(nodes)
    leader.propose({"op": "max_volume_id", "value": 7}, timeout=5)
    time.sleep(0.3)
    for n in nodes:
        n.stop()
    # restart one node from disk: state machine replays to the same value
    replayed = []
    node = RaftNode("node0", ["node0", "node1", "node2"], replayed.append,
                    transport=LocalTransport(), state_dir=str(tmp_path))
    assert any(c.get("value") == 7 for c in replayed)
    assert node.term >= 1


def test_raft_compaction(tmp_path):
    transport, nodes, applied = _mk_cluster_nodes(state_dir=str(tmp_path))
    for n in nodes:
        n.start()
    leader = _wait_leader(nodes)
    for v in range(10):
        leader.propose({"op": "max_volume_id", "value": v}, timeout=5)
    time.sleep(0.3)
    leader.snapshot_fn = lambda: {"max_volume_id": 9}
    leader.compact()
    assert leader.snapshot_index > 0 and len(leader.log) == 0
    for n in nodes:
        n.stop()


def test_raft_install_snapshot_catches_up_lagging_follower():
    """A follower partitioned past the leader's compaction point must
    receive the state-machine snapshot (InstallSnapshot, Raft §7) — with a
    non-idempotent command stream, missing entries would otherwise silently
    diverge the follower's state machine."""
    transport = LocalTransport()
    ids = [f"node{i}" for i in range(3)]
    states = {i: [] for i in ids}  # append-log: NOT idempotent
    nodes = []
    for i in ids:
        def apply(cmd, _s=states[i]):
            _s.append(cmd["v"])

        def snap(_s=states[i]):
            return list(_s)

        def restore(data, _s=states[i]):
            _s[:] = data

        node = RaftNode(i, list(ids), apply, transport=transport,
                        snapshot_fn=snap, restore_fn=restore)
        transport.register(node)
        nodes.append(node)
    for n in nodes:
        n.start()
    try:
        leader = _wait_leader(nodes)
        follower = next(n for n in nodes if n.role != LEADER)
        transport.partitioned.add(follower.node_id)
        for v in range(1, 7):
            leader.propose({"v": v}, timeout=5)
        # compact the leader's log past everything the follower has seen
        leader.compact()
        assert leader.snapshot_index >= 6 and len(leader.log) == 0
        transport.partitioned.discard(follower.node_id)
        deadline = time.time() + 5
        while time.time() < deadline and \
                states[follower.node_id] != [1, 2, 3, 4, 5, 6]:
            time.sleep(0.05)
        assert states[follower.node_id] == [1, 2, 3, 4, 5, 6]
        assert follower.snapshot_index >= 6  # arrived via InstallSnapshot
        # and the follower keeps participating: new entries still replicate
        leader.propose({"v": 7}, timeout=5)
        deadline = time.time() + 5
        while time.time() < deadline and states[follower.node_id][-1] != 7:
            time.sleep(0.05)
        assert states[follower.node_id] == [1, 2, 3, 4, 5, 6, 7]
    finally:
        for n in nodes:
            n.stop()


# -- live 3-master cluster -------------------------------------------------

@pytest.fixture()
def ha_cluster(tmp_path):
    ports = [_free_port() for _ in range(3)]
    addrs = [f"localhost:{p}" for p in ports]
    masters = []
    for p in ports:
        ms = MasterServer(ip="localhost", port=p, volume_size_limit_mb=64,
                          peers=list(addrs), raft_dir=str(tmp_path))
        ms.start(vacuum_interval=3600)
        masters.append(ms)
    vsrv = VolumeServer(directories=[str(tmp_path / "v")],
                        master=",".join(addrs), ip="localhost",
                        port=_free_port(), pulse_seconds=1)
    vsrv.start()
    yield masters, vsrv, addrs
    vsrv.stop()
    for ms in masters:
        ms.stop()
    rpc.reset_channels()


def _wait_master_leader(masters, timeout=15.0):
    """Wait for exactly one live MasterServer to claim leadership."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        leaders = [m for m in masters if m.is_leader()]
        if len(leaders) == 1:
            return leaders[0]
        time.sleep(0.1)
    return None


def test_master_ha_leader_and_assign(ha_cluster):
    masters, vsrv, addrs = ha_cluster
    leader = _wait_master_leader(masters)
    assert leader is not None
    # volume server finds its way to the leader and registers
    deadline = time.time() + 15
    while time.time() < deadline and not leader.topo.nodes:
        time.sleep(0.1)
    assert leader.topo.nodes
    # assign works on the leader; followers refuse with a leader hint
    r = requests.get(
        f"http://{leader.address}/dir/assign?count=1", timeout=10).json()
    assert "fid" in r, r
    follower = next(m for m in masters if m is not leader)
    r = requests.get(
        f"http://{follower.address}/dir/assign?count=1", timeout=10).json()
    assert "error" in r and r.get("leader") == leader.address
    # raft status endpoint
    st = requests.get(f"http://{leader.address}/cluster/raft/status",
                      timeout=10).json()
    assert st["role"] == "leader"
    # replicated max_volume_id reached the followers
    deadline = time.time() + 5
    while time.time() < deadline and \
            follower.topo.max_volume_id < leader.topo.max_volume_id:
        time.sleep(0.05)
    assert follower.topo.max_volume_id >= leader.topo.max_volume_id > 0
    # followers proxy lookups to the leader (their own topology is empty)
    vid = requests.get(
        f"http://{leader.address}/dir/assign?count=1",
        timeout=10).json()["fid"].split(",")[0]
    lr = requests.get(
        f"http://{follower.address}/dir/lookup?volumeId={vid}",
        timeout=10).json()
    assert lr.get("locations"), lr


def test_raft_membership_add_remove():
    """cluster.raft.add/remove semantics: a replicated config entry grows
    the voter set (new node catches up) and shrinks it again."""
    transport = LocalTransport()
    applied = {f"m{i}": [] for i in range(3)}
    nodes = {}
    for i in ("m0", "m1"):
        n = RaftNode(i, ["m0", "m1"], applied[i].append, transport=transport)
        transport.register(n)
        nodes[i] = n
        n.start()
    try:
        leader = _wait_leader(list(nodes.values()))
        for v in range(1, 4):
            leader.propose({"op": "max_volume_id", "value": v})

        # joiner starts knowing the existing members
        n2 = RaftNode("m2", ["m0", "m1", "m2"], applied["m2"].append,
                      transport=transport)
        transport.register(n2)
        nodes["m2"] = n2
        n2.start()
        leader.add_peer("m2")
        assert "m2" in leader.peers

        leader.propose({"op": "max_volume_id", "value": 9})
        deadline = time.time() + 5
        while time.time() < deadline and (
                not applied["m2"] or applied["m2"][-1]["value"] != 9):
            time.sleep(0.05)
        assert applied["m2"] and applied["m2"][-1]["value"] == 9, \
            "new voter did not catch up"

        # followers learned the config too
        follower = next(n for i, n in nodes.items()
                        if i != leader.node_id and i != "m2")
        assert "m2" in follower.peers

        leader.remove_peer("m2")
        assert "m2" not in leader.peers
        # a fresh leader may have emerged during the config change
        leader = _wait_leader([nodes["m0"], nodes["m1"]])
        leader.propose({"op": "max_volume_id", "value": 11})
        time.sleep(0.5)
        # removed voter no longer receives appends... (it may never learn
        # of its own removal — the leader stops replicating to it — but
        # members refuse its votes/appends without adopting its term)
        assert applied["m2"][-1]["value"] != 11, applied["m2"]
        # ...and cannot disturb the live cluster: leadership stays put
        # through m2's would-be election timeouts
        time.sleep(1.2)
        live = _wait_leader([nodes["m0"], nodes["m1"]])
        assert live.node_id != "m2"
        live.propose({"op": "max_volume_id", "value": 12})
    finally:
        for n in nodes.values():
            n.stop()


def test_shell_raft_remove_live(ha_cluster):
    """cluster.raft.remove against a live 3-master group: membership
    shrinks, the removed master stops participating, and the remaining
    pair keeps serving assigns (command_cluster_raft_remove.go)."""
    import io

    from seaweedfs_tpu.operation import assign
    from seaweedfs_tpu.shell.env import CommandEnv
    from seaweedfs_tpu.shell.registry import run_command

    masters, vsrv, addrs = ha_cluster
    leader = _wait_master_leader(masters)
    assert leader is not None
    victim = next(m for m in masters if m is not leader)

    env = CommandEnv(leader.address)
    out = io.StringIO()
    assert run_command(env, "lock", out) == 0
    assert run_command(
        env, f"cluster.raft.remove -id={victim.address}", out) == 0
    assert victim.address in out.getvalue()

    # membership on the leader no longer includes the victim
    deadline = time.time() + 10
    while time.time() < deadline and \
            victim.address in leader.raft.status().get("peers", []):
        time.sleep(0.1)
    assert victim.address not in leader.raft.status().get("peers", [])

    # the remaining group still assigns. Leadership may have moved during
    # the config change, so try every surviving master each round.
    import grpc as _grpc

    survivors = [m for m in masters if m is not victim]
    deadline = time.time() + 25
    last_err = "no attempt"
    ok = False
    while time.time() < deadline and not ok:
        for m in survivors:
            try:
                a = assign(m.address)
                if not a.error:
                    ok = True
                    break
                last_err = a.error
            except _grpc.RpcError as e:
                last_err = str(e)
        time.sleep(0.3)
    assert ok, last_err
