"""Bit-identity and semantics of the two ErasureCoder backends.

The TPU (bitsliced matmul) and CPU (table) backends must agree byte-for-byte
on the full 4-call surface the reference uses
(/root/reference/weed/storage/erasure_coding/ec_encoder.go:179,270;
store_ec.go:384).
"""

import itertools

import numpy as np
import pytest

from seaweedfs_tpu.models.coder import new_coder
from seaweedfs_tpu.ops import gf256
from seaweedfs_tpu.ops.rs_jax import RSCodecJax, gf_matrix_to_bits, gf_matmul_bits

GEOMETRIES = [(10, 4), (6, 3), (12, 4), (4, 2)]


def _rand(k, b, seed=0):
    return np.random.default_rng(seed).integers(0, 256, (k, b)).astype(np.uint8)


def test_bit_matrix_action_matches_gf_mul():
    rng = np.random.default_rng(7)
    m = rng.integers(0, 256, (3, 5)).astype(np.uint8)
    data = rng.integers(0, 256, (5, 97)).astype(np.uint8)
    want = gf256.gf_matmul(m, data)
    got = np.asarray(gf_matmul_bits(gf_matrix_to_bits(m), data))
    assert np.array_equal(want, got)


@pytest.mark.parametrize("k,m", GEOMETRIES)
def test_encode_backends_identical(k, m):
    tpu = new_coder(k, m, "tpu")
    cpu = new_coder(k, m, "cpu")
    for b in (1, 50, 256, 1000, 4096):
        data = _rand(k, b, seed=b)
        p1 = np.asarray(tpu.encode_parity(data))
        p2 = cpu.encode_parity(data)
        assert np.array_equal(p1, p2), f"parity mismatch k={k} m={m} b={b}"


@pytest.mark.parametrize("k,m", [(10, 4), (6, 3)])
def test_reconstruct_any_subset(k, m):
    tpu = new_coder(k, m, "tpu")
    cpu = new_coder(k, m, "cpu")
    data = _rand(k, 333, seed=9)
    shards = np.asarray(tpu.encode(np.concatenate([data, np.zeros((m, 333), np.uint8)])))
    total = k + m
    rng = np.random.default_rng(11)
    # several random loss patterns, including max-loss
    patterns = [rng.choice(total, size=m, replace=False) for _ in range(6)]
    patterns.append(np.arange(m))  # first m lost
    patterns.append(np.arange(total - m, total))  # all parity lost
    for lost in patterns:
        have = {i: shards[i] for i in range(total) if i not in set(int(x) for x in lost)}
        rec_t = tpu.reconstruct(dict(have))
        rec_c = cpu.reconstruct(dict(have))
        assert set(rec_t) == set(rec_c) == set(int(x) for x in lost)
        for i in rec_t:
            assert np.array_equal(np.asarray(rec_t[i]), shards[i]), f"shard {i}"
            assert np.array_equal(rec_c[i], shards[i])


def test_reconstruct_data_only_returns_data_shards():
    k, m = 10, 4
    tpu = new_coder(k, m, "tpu")
    data = _rand(k, 100)
    shards = np.asarray(
        tpu.encode(np.concatenate([data, np.zeros((m, 100), np.uint8)]))
    )
    have = {i: shards[i] for i in range(k + m) if i not in (1, 12)}
    rec = tpu.reconstruct_data(have)
    assert set(rec) == {1}
    assert np.array_equal(np.asarray(rec[1]), shards[1])


def test_verify():
    k, m = 10, 4
    tpu = new_coder(k, m, "tpu")
    data = _rand(k, 64)
    shards = np.asarray(
        tpu.encode(np.concatenate([data, np.zeros((m, 64), np.uint8)]))
    )
    assert tpu.verify(shards)
    bad = shards.copy()
    bad[13, 0] ^= 1
    assert not tpu.verify(bad)


def test_zero_data_zero_parity():
    tpu = new_coder(10, 4, "tpu")
    parity = np.asarray(tpu.encode_parity(np.zeros((10, 128), np.uint8)))
    assert not parity.any()


def test_systematic_passthrough():
    """Data shards are the data itself — the reference relies on this for
    direct shard reads (ec_test.go readOneInterval)."""
    tpu = new_coder(10, 4, "tpu")
    data = _rand(10, 200, seed=21)
    shards = np.asarray(
        tpu.encode(np.concatenate([data, np.zeros((4, 200), np.uint8)]))
    )
    assert np.array_equal(shards[:10], data)


def test_exhaustive_two_loss_small_geometry():
    k, m = 4, 2
    tpu = new_coder(k, m, "tpu")
    data = _rand(k, 77, seed=5)
    shards = np.asarray(
        tpu.encode(np.concatenate([data, np.zeros((m, 77), np.uint8)]))
    )
    for lost in itertools.combinations(range(k + m), m):
        have = {i: shards[i] for i in range(k + m) if i not in lost}
        rec = tpu.reconstruct(have)
        for i in lost:
            assert np.array_equal(np.asarray(rec[i]), shards[i])


def test_reconstruct_stacked_bit_identical_to_dict_path():
    """The pre-stacked survivor form (column-permuted fused matrix,
    ec_files rebuild hot path) must match the dict path byte-for-byte,
    including surplus survivors (P > k) and arbitrary caller row order."""
    tpu = new_coder(10, 4, "tpu")
    data = _rand(10, 555, seed=33)
    shards = np.asarray(
        tpu.encode(np.concatenate([data, np.zeros((4, 555), np.uint8)]))
    )
    lost = (0, 5, 12)
    pres_ids = tuple(i for i in range(14) if i not in lost)
    # deliberately shuffle the caller's row order
    order = pres_ids[::-1]
    stacked = np.stack([shards[i] for i in order])
    mids, rows = tpu.reconstruct_stacked(order, stacked)
    assert mids == lost
    rows = np.asarray(rows)
    ref = tpu.reconstruct({i: shards[i] for i in pres_ids})
    for j, i in enumerate(mids):
        assert np.array_equal(rows[j], shards[i])
        assert np.array_equal(rows[j], np.asarray(ref[i]))
    # data_only limits regeneration to data shards
    mids_d, rows_d = tpu.reconstruct_stacked(order, stacked, data_only=True)
    assert mids_d == (0, 5)
    assert np.array_equal(np.asarray(rows_d)[0], shards[0])
    # nothing missing -> empty result
    all_ids = tuple(range(14))
    mids_n, rows_n = tpu.reconstruct_stacked(all_ids, shards)
    assert mids_n == () and np.asarray(rows_n).shape == (0, 555)
