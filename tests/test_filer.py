"""Filer tests: store SPI, chunk interval math, and the HTTP/gRPC namespace
over a live in-process cluster (SURVEY.md §2.5)."""

import io
import socket
import time

import numpy as np
import pytest
import requests

from seaweedfs_tpu.filer import Attr, Entry, Filer
from seaweedfs_tpu.filer.filechunks import (
    non_overlapping_visible_intervals,
    total_size,
    view_from_chunks,
)
from seaweedfs_tpu.filer.filerstore import get_store
from seaweedfs_tpu.filer.filer import NotEmpty, NotFound
from seaweedfs_tpu.pb import filer_pb2, rpc
from seaweedfs_tpu.server.filer import FilerServer
from seaweedfs_tpu.server.master import MasterServer
from seaweedfs_tpu.server.volume import VolumeServer


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


# -- pure store/chunk tests ------------------------------------------------

@pytest.mark.parametrize("store_name", ["memory", "sqlite"])
def test_store_crud_and_listing(store_name):
    store = get_store(store_name)
    f = Filer(store)
    f.create_entry(Entry(full_path="/a/b/c.txt", attr=Attr(mtime=1)))
    assert f.find_entry("/a/b/c.txt").attr.mtime == 1
    assert f.find_entry("/a/b").is_directory  # auto-created parent
    for i in range(5):
        f.create_entry(Entry(full_path=f"/a/b/f{i}", attr=Attr(mtime=i)))
    names = [e.name for e in f.list_entries("/a/b")]
    assert names == ["c.txt", "f0", "f1", "f2", "f3", "f4"]
    names = [e.name for e in f.list_entries("/a/b", start="f1")]
    assert names == ["f2", "f3", "f4"]
    names = [e.name for e in f.list_entries("/a/b", prefix="f")]
    assert len(names) == 5
    with pytest.raises(NotEmpty):
        f.delete_entry("/a/b")
    f.delete_entry("/a/b", recursive=True)
    with pytest.raises(NotFound):
        f.find_entry("/a/b/c.txt")
    # kv
    store.kv_put(b"k", b"v")
    assert store.kv_get(b"k") == b"v"


def test_rename_subtree():
    f = Filer(get_store("memory"))
    f.create_entry(Entry(full_path="/x/1"))
    f.create_entry(Entry(full_path="/x/sub/2"))
    f.rename("/x", "/y")
    assert f.find_entry("/y/1")
    assert f.find_entry("/y/sub/2")
    with pytest.raises(NotFound):
        f.find_entry("/x/1")


def _chunk(fid, offset, size, ts):
    return filer_pb2.FileChunk(file_id=fid, offset=offset, size=size,
                               modified_ts_ns=ts)


def test_visible_intervals_shadowing():
    # chunk B (newer) overwrites the middle of chunk A
    a = _chunk("a", 0, 100, 1)
    b = _chunk("b", 30, 20, 2)
    iv = non_overlapping_visible_intervals([a, b])
    assert [(s, e, c.file_id) for s, e, c in iv] == [
        (0, 30, "a"), (30, 50, "b"), (50, 100, "a")]
    assert total_size([a, b]) == 100
    views = view_from_chunks([a, b], 20, 40)
    assert [(v.file_id, v.chunk_offset, v.size, v.logical_offset)
            for v in views] == [("a", 20, 10, 20), ("b", 0, 20, 30),
                                ("a", 50, 10, 50)]


def test_metadata_event_log():
    f = Filer(get_store("memory"))
    t0 = time.time_ns()
    f.create_entry(Entry(full_path="/d/x"))
    f.delete_entry("/d/x")
    events, cursor = f.read_events(t0)
    kinds = [(bool(m.event_notification.old_entry.name),
              bool(m.event_notification.new_entry.name)) for m in events
             if "/d" == m.directory]
    assert (False, True) in kinds  # create
    assert (True, False) in kinds  # delete
    assert cursor > t0


def test_meta_log_survives_restart(tmp_path):
    """filer_notify.go:70/:116 — events persist under /topics/.system/log and
    replay across a filer restart for point-in-time resume."""
    db = str(tmp_path / "filer.db")
    f = Filer(get_store("sqlite", db_path=db))
    t0 = time.time_ns() - 1
    for i in range(10):
        f.create_entry(Entry(full_path=f"/d/f{i}"))
    f.delete_entry("/d/f0")
    f.meta_log.close()
    f.store.close()

    f2 = Filer(get_store("sqlite", db_path=db))
    events, cursor = f2.read_events(t0)
    names = [m.event_notification.new_entry.name for m in events
             if m.directory == "/d" and m.event_notification.new_entry.name]
    assert names == [f"f{i}" for i in range(10)]
    deletes = [m for m in events if m.directory == "/d"
               and m.event_notification.old_entry.name == "f0"
               and not m.event_notification.new_entry.name]
    assert deletes, "delete event lost across restart"
    assert cursor == events[-1].ts_ns

    # resume mid-stream: cursor after the 5th create sees only the tail
    mid = events[4].ts_ns
    tail, _ = f2.read_events(mid)
    tail_names = [m.event_notification.new_entry.name for m in tail
                  if m.directory == "/d" and m.event_notification.new_entry.name]
    assert tail_names == [f"f{i}" for i in range(5, 10)]
    f2.store.close()


def test_notification_queue_receives_events():
    """filer_notify.go NotifyUpdateEvent -> Queue.SendMessage: a configured
    publisher sees every metadata event."""
    from seaweedfs_tpu.notification import MemoryQueue

    f = Filer(get_store("memory"))
    q = MemoryQueue()
    f.notification_queue = q
    f.create_entry(Entry(full_path="/nq/file.txt"))
    f.delete_entry("/nq/file.txt")
    keys = [k for k, _ in q.events]
    assert "/nq/file.txt" in keys
    creates = [m for k, m in q.events if m.new_entry.name == "file.txt"]
    deletes = [m for k, m in q.events
               if m.old_entry.name == "file.txt" and not m.new_entry.name]
    assert creates and deletes


def test_meta_log_outlives_deque_window():
    """A subscriber that lagged past the bounded deque reads the persisted
    log instead of silently losing events (round-1 weak #8)."""
    f = Filer(get_store("memory"), log_capacity=4)
    t0 = time.time_ns() - 1
    for i in range(25):
        f.create_entry(Entry(full_path=f"/lag/f{i}"))
    events, _ = f.read_events(t0)
    names = [m.event_notification.new_entry.name for m in events
             if m.directory == "/lag" and m.event_notification.new_entry.name]
    assert names == [f"f{i}" for i in range(25)]


# -- live cluster ----------------------------------------------------------

@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    mport = _free_port()
    master = MasterServer(ip="localhost", port=mport, volume_size_limit_mb=64)
    master.start(vacuum_interval=3600)
    vsrv = VolumeServer(
        directories=[str(tmp_path_factory.mktemp("vol"))],
        master=f"localhost:{mport}", ip="localhost", port=_free_port(),
        pulse_seconds=1)
    vsrv.start()
    fsrv = FilerServer(ip="localhost", port=_free_port(),
                       master=f"localhost:{mport}",
                       store_dir=str(tmp_path_factory.mktemp("filer")),
                       chunk_size=64 * 1024)
    fsrv.start()
    deadline = time.time() + 10
    while time.time() < deadline and not master.topo.nodes:
        time.sleep(0.05)
    yield master, vsrv, fsrv
    fsrv.stop()
    vsrv.stop()
    master.stop()
    rpc.reset_channels()


def test_filer_http_roundtrip(cluster):
    _, _, fsrv = cluster
    base = f"http://{fsrv.address}"
    rng = np.random.default_rng(5)
    # multi-chunk file (chunk_size 64k, write 200k)
    payload = rng.integers(0, 256, size=200_000, dtype=np.uint8).tobytes()
    r = requests.put(f"{base}/docs/big.bin", data=payload, timeout=60,
                     headers={"Content-Type": "application/x-test"})
    assert r.status_code == 201, r.text
    got = requests.get(f"{base}/docs/big.bin", timeout=60)
    assert got.status_code == 200
    assert got.content == payload
    assert got.headers["Content-Type"] == "application/x-test"

    # range read spanning a chunk boundary
    got = requests.get(f"{base}/docs/big.bin", timeout=60,
                       headers={"Range": "bytes=60000-70000"})
    assert got.status_code == 206
    assert got.content == payload[60000:70001]

    # directory listing
    lst = requests.get(f"{base}/docs/", timeout=30).json()
    assert [e["FullPath"] for e in lst["Entries"]] == ["/docs/big.bin"]
    assert lst["Entries"][0]["FileSize"] == len(payload)

    # overwrite GCs old chunks and serves new content
    r = requests.put(f"{base}/docs/big.bin", data=b"tiny", timeout=60)
    assert r.status_code == 201
    assert requests.get(f"{base}/docs/big.bin", timeout=30).content == b"tiny"

    # delete
    assert requests.delete(f"{base}/docs/big.bin", timeout=30).status_code == 204
    assert requests.get(f"{base}/docs/big.bin", timeout=30).status_code == 404


def test_filer_grpc_surface(cluster):
    _, _, fsrv = cluster
    stub = rpc.filer_stub(rpc.grpc_address(fsrv.address))
    # create via gRPC
    e = filer_pb2.Entry(name="hello.txt", is_directory=False,
                        content=b"inline content")
    e.attributes.mtime = int(time.time())
    resp = stub.CreateEntry(filer_pb2.CreateEntryRequest(
        directory="/grpc", entry=e), timeout=10)
    assert not resp.error
    lk = stub.LookupDirectoryEntry(filer_pb2.LookupDirectoryEntryRequest(
        directory="/grpc", name="hello.txt"), timeout=10)
    assert lk.entry.content == b"inline content"
    # inline content served over HTTP too
    got = requests.get(f"http://{fsrv.address}/grpc/hello.txt", timeout=30)
    assert got.content == b"inline content"
    # listing stream
    names = [r.entry.name for r in stub.ListEntries(
        filer_pb2.ListEntriesRequest(directory="/grpc"), timeout=10)]
    assert names == ["hello.txt"]
    # rename
    stub.AtomicRenameEntry(filer_pb2.AtomicRenameEntryRequest(
        old_directory="/grpc", old_name="hello.txt",
        new_directory="/grpc", new_name="renamed.txt"), timeout=10)
    assert requests.get(f"http://{fsrv.address}/grpc/renamed.txt",
                        timeout=30).status_code == 200
    # config
    conf = stub.GetFilerConfiguration(
        filer_pb2.GetFilerConfigurationRequest(), timeout=10)
    assert conf.masters


def test_filer_subscribe_metadata(cluster):
    _, _, fsrv = cluster
    stub = rpc.filer_stub(rpc.grpc_address(fsrv.address))
    since = time.time_ns()
    got = []

    import threading

    def consume():
        for msg in stub.SubscribeMetadata(
                filer_pb2.SubscribeMetadataRequest(
                    client_name="t", path_prefix="/sub", since_ns=since),
                timeout=10):
            got.append(msg)
            if len(got) >= 2:
                break

    t = threading.Thread(target=consume, daemon=True)
    t.start()
    time.sleep(0.3)
    requests.put(f"http://{fsrv.address}/sub/a.txt", data=b"one", timeout=30)
    requests.delete(f"http://{fsrv.address}/sub/a.txt", timeout=30)
    t.join(timeout=10)
    assert len(got) >= 2
    assert got[0].event_notification.new_entry.name == "a.txt"


def test_conditional_get_304(cluster):
    """If-None-Match / If-Modified-Since -> 304 (filer_server_handlers_read
    and volume_server_handlers_read conditional paths)."""
    master, vsrv, fsrv = cluster
    requests.post(f"http://{fsrv.address}/cond/x.txt",
                  files={"file": ("x.txt", b"cacheable")}, timeout=10)
    r = requests.get(f"http://{fsrv.address}/cond/x.txt", timeout=10)
    assert r.status_code == 200
    etag = r.headers["ETag"]
    last_mod = r.headers.get("Last-Modified")

    r2 = requests.get(f"http://{fsrv.address}/cond/x.txt",
                      headers={"If-None-Match": etag}, timeout=10)
    assert r2.status_code == 304 and not r2.content
    assert requests.get(f"http://{fsrv.address}/cond/x.txt",
                        headers={"If-None-Match": '"nope"'},
                        timeout=10).status_code == 200
    if last_mod:
        r3 = requests.get(f"http://{fsrv.address}/cond/x.txt",
                          headers={"If-Modified-Since": last_mod}, timeout=10)
        assert r3.status_code == 304

    # volume server conditional path via a direct fid
    from seaweedfs_tpu.operation import assign, upload_data

    a = assign(master.address)
    upload_data(f"http://{a.url}/{a.fid}", b"needle-cond")
    r = requests.get(f"http://{a.url}/{a.fid}", timeout=10)
    assert r.status_code == 200
    etag = r.headers["ETag"]
    assert requests.get(f"http://{a.url}/{a.fid}",
                        headers={"If-None-Match": etag},
                        timeout=10).status_code == 304
    lm = r.headers.get("Last-Modified")
    if lm:
        assert requests.get(f"http://{a.url}/{a.fid}",
                            headers={"If-Modified-Since": lm},
                            timeout=10).status_code == 304


def test_conditional_get_precedence_and_ranges(cluster):
    """RFC 7232 §3.3: a non-matching If-None-Match must win over a stale
    If-Modified-Since; ranged revalidation also gets 304 + ETag."""
    _, _, fsrv = cluster
    requests.post(f"http://{fsrv.address}/cond/p.txt",
                  files={"file": ("p.txt", b"first body")}, timeout=10)
    r = requests.get(f"http://{fsrv.address}/cond/p.txt", timeout=10)
    last_mod = r.headers.get("Last-Modified")

    # same-second overwrite: mtime unchanged, etag changes
    requests.post(f"http://{fsrv.address}/cond/p.txt",
                  files={"file": ("p.txt", b"second body!")}, timeout=10)
    r2 = requests.get(
        f"http://{fsrv.address}/cond/p.txt",
        headers={"If-None-Match": r.headers["ETag"],
                 "If-Modified-Since": last_mod or
                 "Thu, 01 Jan 2037 00:00:00 GMT"},
        timeout=10)
    assert r2.status_code == 200 and r2.content == b"second body!"

    # ranged revalidation honors conditionals and carries the ETag on 206
    etag = r2.headers["ETag"]
    r3 = requests.get(f"http://{fsrv.address}/cond/p.txt",
                      headers={"Range": "bytes=0-5",
                               "If-None-Match": etag}, timeout=10)
    assert r3.status_code == 304
    r4 = requests.get(f"http://{fsrv.address}/cond/p.txt",
                      headers={"Range": "bytes=0-5"}, timeout=10)
    assert r4.status_code == 206 and r4.headers.get("ETag") == etag
    assert r4.content == b"second"


def test_stream_file_yields_per_chunk(cluster):
    """GETs stream chunk-by-chunk (StreamContent): filer memory stays one
    chunk deep instead of materializing the whole file."""
    _, _, fsrv = cluster
    rng = np.random.default_rng(77)
    payload = rng.integers(0, 256, size=200_000, dtype=np.uint8).tobytes()
    requests.put(f"http://{fsrv.address}/stream/big.bin", data=payload,
                 timeout=30)
    entry = fsrv.filer.find_entry("/stream/big.bin")
    pieces = list(fsrv.stream_file(entry))
    assert len(pieces) >= 3  # 64KB chunks -> at least 4 views
    assert b"".join(pieces) == payload
    # offset/size streaming agrees with the byte range
    part = b"".join(fsrv.stream_file(entry, 70_000, 50_000))
    assert part == payload[70_000:120_000]


def test_range_parsing_edge_cases(cluster):
    """Suffix/oversized/unsatisfiable ranges (RFC 7233): clamped lengths,
    416 for out-of-bounds, suffix 'bytes=-N'."""
    _, _, fsrv = cluster
    body = bytes(range(100))
    requests.put(f"http://{fsrv.address}/rng/f.bin", data=body, timeout=10)
    base = f"http://{fsrv.address}/rng/f.bin"

    # oversized range clamps (Content-Length must match delivered bytes)
    r = requests.get(base, headers={"Range": "bytes=0-9999999"}, timeout=10)
    assert r.status_code == 206
    assert int(r.headers["Content-Length"]) == 100 == len(r.content)
    # suffix range: last 10 bytes
    r = requests.get(base, headers={"Range": "bytes=-10"}, timeout=10)
    assert r.status_code == 206 and r.content == body[-10:]
    # unsatisfiable
    r = requests.get(base, headers={"Range": "bytes=200-300"}, timeout=10)
    assert r.status_code == 416
    assert r.headers.get("Content-Range") == "bytes */100"
    r = requests.get(base, headers={"Range": "bytes=5-2"}, timeout=10)
    assert r.status_code == 416
    # malformed -> full body
    r = requests.get(base, headers={"Range": "bytes=abc-def"}, timeout=10)
    assert r.status_code == 200 and r.content == body


def test_chunked_transfer_encoding_put(cluster):
    """PUT with Transfer-Encoding: chunked streams through the autochunker
    (no Content-Length): body lands intact, keep-alive stays usable."""
    _, _, fsrv = cluster
    rng = np.random.default_rng(21)
    payload = rng.integers(0, 256, size=150_000, dtype=np.uint8).tobytes()

    def gen():
        for off in range(0, len(payload), 10_000):
            yield payload[off:off + 10_000]

    s = requests.Session()
    r = s.put(f"http://{fsrv.address}/te/chunked.bin", data=gen(), timeout=30)
    assert r.status_code == 201, r.text
    r = s.get(f"http://{fsrv.address}/te/chunked.bin", timeout=30)
    assert r.status_code == 200 and r.content == payload
    # next request on the same keep-alive connection still parses
    r = s.get(f"http://{fsrv.address}/te/chunked.bin",
              headers={"Range": "bytes=0-9"}, timeout=30)
    assert r.status_code == 206 and r.content == payload[:10]


def test_truncated_chunked_put_rejected(cluster):
    """A chunked body that ends without the 0-size terminator must fail,
    not silently store a truncated file."""
    import socket as sk

    _, _, fsrv = cluster
    host, port = fsrv.address.split(":")
    conn = sk.create_connection((host, int(port)), timeout=10)
    conn.sendall(b"PUT /trunc/x.bin HTTP/1.1\r\nHost: x\r\n"
                 b"Transfer-Encoding: chunked\r\n\r\n"
                 b"10\r\n0123456789abcdef\r\n"
                 b"10\r\npartial")  # chunk promises 16 bytes, sends 7
    conn.shutdown(sk.SHUT_WR)
    resp = b""
    while True:
        piece = conn.recv(4096)
        if not piece:
            break
        resp += piece
    conn.close()
    assert b"500" in resp.split(b"\r\n", 1)[0], resp[:100]
    assert requests.get(f"http://{fsrv.address}/trunc/x.bin",
                        timeout=10).status_code == 404


def test_put_with_no_writable_volumes_returns_500(tmp_path):
    """A filer PUT when assign fails (no volume servers) must answer a
    clean 500 JSON, not abort the connection."""
    from seaweedfs_tpu.server.master import MasterServer

    master = MasterServer(ip="localhost", port=_free_port(),
                          volume_size_limit_mb=64)
    master.start(vacuum_interval=3600)
    fs = FilerServer(ip="localhost", port=_free_port(),
                     master=master.address, store_dir=str(tmp_path / "nf"))
    fs.start()
    try:
        r = requests.put(f"http://localhost:{fs.port}/x/y.bin", data=b"data",
                         timeout=15)
        assert r.status_code == 500 and "error" in r.json()
    finally:
        fs.stop()
        master.stop()
        rpc.reset_channels()
