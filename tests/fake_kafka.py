"""In-process fake Kafka broker: Metadata v0 + Produce v0.

Independently decodes the binary framing the producer in
seaweedfs_tpu/notification/kafka_wire.py emits — including the
MessageSet CRC, which is recomputed and enforced — and stores
(key, value) per partition so tests can assert delivery.
"""

from __future__ import annotations

import socket
import struct
import threading
import zlib


class FakeKafkaBroker:
    def __init__(self, topic: str = "seaweedfs_filer", partitions: int = 3):
        self.topic = topic
        self.npartitions = partitions
        self.messages: dict[int, list[tuple[bytes, bytes]]] = {
            i: [] for i in range(partitions)}
        self.crc_failures = 0
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind(("127.0.0.1", 0))
        self._srv.listen(8)
        self.port = self._srv.getsockname()[1]
        self._stop = False
        threading.Thread(target=self._accept_loop, daemon=True).start()

    @property
    def addr(self) -> str:
        return f"127.0.0.1:{self.port}"

    def close(self):
        self._stop = True
        try:
            self._srv.close()
        except OSError:
            pass

    # -- server loop

    def _accept_loop(self):
        while not self._stop:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn: socket.socket):
        try:
            while True:
                hdr = self._recv(conn, 4)
                if hdr is None:
                    return
                (size,) = struct.unpack(">i", hdr)
                req = self._recv(conn, size)
                if req is None:
                    return
                api_key, api_version, corr = struct.unpack_from(">hhi", req)
                off = 8
                (cid_len,) = struct.unpack_from(">h", req, off)
                off += 2 + cid_len
                if api_key == 3 and api_version == 0:
                    resp = self._metadata(req, off)
                elif api_key == 0 and api_version == 0:
                    resp = self._produce(req, off)
                else:
                    return
                out = struct.pack(">i", corr) + resp
                conn.sendall(struct.pack(">i", len(out)) + out)
        except OSError:
            pass
        finally:
            conn.close()

    @staticmethod
    def _recv(conn: socket.socket, n: int) -> bytes | None:
        out = b""
        while len(out) < n:
            chunk = conn.recv(n - len(out))
            if not chunk:
                return None
            out += chunk
        return out

    # -- RPC handlers

    def _metadata(self, req: bytes, off: int) -> bytes:
        def s(x: str) -> bytes:
            b = x.encode()
            return struct.pack(">h", len(b)) + b

        # one broker (us), one topic, npartitions with leader 0
        out = struct.pack(">i", 1)                        # brokers
        out += struct.pack(">i", 0) + s("127.0.0.1") + \
            struct.pack(">i", self.port)
        out += struct.pack(">i", 1)                       # topics
        out += struct.pack(">h", 0) + s(self.topic)
        out += struct.pack(">i", self.npartitions)
        for pid in range(self.npartitions):
            out += struct.pack(">hii", 0, pid, 0)         # err, id, leader
            out += struct.pack(">i", 1) + struct.pack(">i", 0)   # replicas
            out += struct.pack(">i", 1) + struct.pack(">i", 0)   # isr
        return out

    def _produce(self, req: bytes, off: int) -> bytes:
        _acks, _timeout = struct.unpack_from(">hi", req, off)
        off += 6
        (ntopics,) = struct.unpack_from(">i", req, off)
        off += 4
        resp_topics = b""
        for _ in range(ntopics):
            (tlen,) = struct.unpack_from(">h", req, off)
            off += 2
            topic = req[off:off + tlen].decode()
            off += tlen
            (nparts,) = struct.unpack_from(">i", req, off)
            off += 4
            parts_out = b""
            for _ in range(nparts):
                pid, ms_size = struct.unpack_from(">ii", req, off)
                off += 8
                ms = req[off:off + ms_size]
                off += ms_size
                err, offset = self._ingest(topic, pid, ms)
                parts_out += struct.pack(">ihq", pid, err, offset)
            resp_topics += (struct.pack(">h", tlen) + topic.encode() +
                            struct.pack(">i", nparts) + parts_out)
        return struct.pack(">i", ntopics) + resp_topics

    def _ingest(self, topic: str, pid: int, ms: bytes) -> tuple[int, int]:
        if topic != self.topic or pid not in self.messages:
            return 3, -1                       # UNKNOWN_TOPIC_OR_PARTITION
        off = 0
        last = -1
        while off + 12 <= len(ms):
            _offset, msize = struct.unpack_from(">qi", ms, off)
            off += 12
            msg = ms[off:off + msize]
            off += msize
            (crc,) = struct.unpack_from(">I", msg, 0)
            if zlib.crc32(msg[4:]) & 0xFFFFFFFF != crc:
                self.crc_failures += 1
                return 2, -1                   # CORRUPT_MESSAGE
            p = 6                              # crc4 + magic1 + attrs1
            (klen,) = struct.unpack_from(">i", msg, p)
            p += 4
            key = msg[p:p + klen] if klen >= 0 else b""
            p += max(klen, 0)
            (vlen,) = struct.unpack_from(">i", msg, p)
            p += 4
            value = msg[p:p + vlen] if vlen >= 0 else b""
            self.messages[pid].append((key, value))
            last = len(self.messages[pid]) - 1
        return 0, last
