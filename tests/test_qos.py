"""QoS / admission plane unit coverage (ISSUE 8): token refill
arithmetic under clock-free fake time, tenant-key extraction at both
ingress planes, strict grant priority (background never starves a
blocked foreground writer — and repair never starves behind archival),
and pressure-score monotonicity against synthetic group-commit /
dispatch queue depths.
"""

from __future__ import annotations

import threading
import time

import pytest

from seaweedfs_tpu.qos import (
    BackgroundGovernor,
    Decision,
    GrantLedger,
    QosUnavailable,
    TenantAdmission,
    TokenBucket,
    filer_tenant,
    pressure_score,
    s3_access_key_hint,
    s3_tenant,
)


class FakeClock:
    """Injectable monotonic time: refill arithmetic is tested with zero
    sleeps (no wall-clock flakes)."""

    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


# -- token-bucket refill arithmetic -----------------------------------------

def test_bucket_starts_full_and_deducts():
    clk = FakeClock()
    b = TokenBucket(rate=10, burst=5, now=clk)
    for _ in range(5):
        assert b.try_take(1) == 0.0
    # empty now: the wait hint is the exact refill time for 1 token
    assert b.try_take(1) == pytest.approx(0.1)


def test_bucket_refills_at_rate_capped_at_burst():
    clk = FakeClock()
    b = TokenBucket(rate=10, burst=5, now=clk)
    assert b.try_take(5) == 0.0
    clk.advance(0.25)  # 2.5 tokens back
    assert b.available() == pytest.approx(2.5)
    clk.advance(100.0)  # refill far past burst: capped
    assert b.available() == pytest.approx(5.0)


def test_bucket_wait_hint_scales_with_deficit():
    clk = FakeClock()
    b = TokenBucket(rate=2, burst=4, now=clk)
    assert b.try_take(4) == 0.0
    # 3 tokens wanted, 0 held, rate 2/s -> 1.5s
    assert b.try_take(3) == pytest.approx(1.5)
    clk.advance(0.5)  # 1 token back -> deficit 2 -> 1.0s
    assert b.try_take(3) == pytest.approx(1.0)
    # a failed take deducts nothing
    assert b.available() == pytest.approx(1.0)


def test_bucket_unlimited_when_rate_nonpositive():
    b = TokenBucket(rate=0, now=FakeClock())
    for _ in range(10_000):
        assert b.try_take(100) == 0.0
    assert b.available() == float("inf")


def test_bucket_fractional_rate_accumulates():
    clk = FakeClock()
    b = TokenBucket(rate=0.5, burst=1, now=clk)
    assert b.try_take(1) == 0.0
    assert b.try_take(1) == pytest.approx(2.0)
    clk.advance(1.0)
    assert b.try_take(1) == pytest.approx(1.0)
    clk.advance(1.0)
    assert b.try_take(1) == 0.0


# -- tenant-key extraction ---------------------------------------------------

def test_s3_tenant_sigv4_access_key():
    headers = {"Authorization":
               "AWS4-HMAC-SHA256 Credential=AKIDEXAMPLE/20260803/"
               "us-east-1/s3/aws4_request, SignedHeaders=host, "
               "Signature=abc"}
    assert s3_access_key_hint(headers, "") == "AKIDEXAMPLE"
    assert s3_tenant(headers, "", "mybucket") == "ak:AKIDEXAMPLE"


def test_s3_tenant_presigned_query_forms():
    # SigV4 presigned (URL-encoded credential scope)
    q = "X-Amz-Algorithm=AWS4-HMAC-SHA256&X-Amz-Credential=AKpre%2F2026"
    assert s3_access_key_hint({}, q) == "AKpre"
    # v2 presigned
    assert s3_access_key_hint({}, "AWSAccessKeyId=AKv2&Expires=1") == \
        "AKv2"


def test_s3_tenant_falls_back_to_bucket_then_anonymous():
    assert s3_tenant({}, "", "photos") == "col:photos"
    assert s3_tenant({}, "", "") == "anonymous"


def test_filer_tenant_collection_param_wins():
    assert filer_tenant("/any/path", "geo") == "col:geo"


def test_filer_tenant_bucket_path_fallback():
    assert filer_tenant("/buckets/media/a/b.jpg", "") == "col:media"
    # dot-prefixed system dirs are not tenants
    assert filer_tenant("/buckets/.uploads/x", "") == "anonymous"
    assert filer_tenant("/topics/chat/p0", "") == "anonymous"
    assert filer_tenant("/buckets/", "") == "anonymous"


# -- TenantAdmission ---------------------------------------------------------

def _admission(monkeypatch, clk, **env):
    for k, v in env.items():
        monkeypatch.setenv(k, v)
    adm = TenantAdmission("test", now=clk)
    adm.refresh_config()
    return adm


def test_admission_defaults_to_observe_only(monkeypatch):
    monkeypatch.delenv("SWFS_QOS_TENANT_RPS", raising=False)
    monkeypatch.delenv("SWFS_QOS_TENANT_OVERRIDES", raising=False)
    adm = TenantAdmission("test", now=FakeClock())
    for _ in range(1000):
        assert adm.admit("col:x").admitted
    assert adm.rejected == 0


def test_admission_rejects_over_rate_with_retry_hint(monkeypatch):
    clk = FakeClock()
    adm = _admission(monkeypatch, clk, SWFS_QOS_TENANT_RPS="10",
                     SWFS_QOS_TENANT_BURST="3")
    for _ in range(3):
        assert adm.admit("col:x", trace_id="t1").admitted
    d = adm.admit("col:x", trace_id="feedbead" * 4, detail="PUT /x")
    assert isinstance(d, Decision) and not d.admitted
    assert d.retry_after_s >= 0.05
    # the rejection log carries the trace id — the explainability handle
    rej = adm.recent_rejections()[-1]
    assert rej["traceId"] == "feedbead" * 4
    assert rej["tenant"] == "col:x"
    # refill under fake time re-admits
    clk.advance(1.0)
    assert adm.admit("col:x").admitted


def test_admission_per_tenant_override_and_isolation(monkeypatch):
    clk = FakeClock()
    adm = _admission(
        monkeypatch, clk, SWFS_QOS_TENANT_RPS="0",
        SWFS_QOS_TENANT_OVERRIDES='{"col:noisy": {"rps": 2, "burst": 2}}')
    # the noisy tenant is capped...
    assert adm.admit("col:noisy").admitted
    assert adm.admit("col:noisy").admitted
    assert not adm.admit("col:noisy").admitted
    # ...while other tenants ride the unlimited default
    for _ in range(100):
        assert adm.admit("col:quiet").admitted


def test_admission_tenant_lru_is_bounded(monkeypatch):
    from seaweedfs_tpu.qos import admission as adm_mod

    clk = FakeClock()
    adm = _admission(monkeypatch, clk, SWFS_QOS_TENANT_RPS="1000")
    old_cap, adm_mod.MAX_TENANTS = adm_mod.MAX_TENANTS, 8
    try:
        for i in range(100):  # hostile key spray
            adm.admit(f"ak:spray{i}")
        assert len(adm._buckets) <= 8
    finally:
        adm_mod.MAX_TENANTS = old_cap


def test_admission_status_snapshot(monkeypatch):
    adm = _admission(monkeypatch, FakeClock(), SWFS_QOS_TENANT_RPS="5",
                     SWFS_QOS_TENANT_BURST="5")
    for _ in range(7):
        adm.admit("col:x", trace_id="tid1")
    st = adm.status()
    assert st["plane"] == "test"
    assert st["admitted"] == 5 and st["rejected"] == 2
    assert "col:x" in st["tenants"]
    assert len(st["recentRejections"]) == 2


# -- GrantLedger: strict priority by reservation ----------------------------

def _ledger(monkeypatch, clk, mbps: float):
    monkeypatch.setenv("SWFS_QOS_BG_MBPS", str(mbps))
    led = GrantLedger(now=clk)
    led._rate_read_at = -1e9  # drop the TTL cache
    return led


def test_ledger_unconfigured_grants_everything(monkeypatch):
    monkeypatch.delenv("SWFS_QOS_BG_MBPS", raising=False)
    led = GrantLedger(now=FakeClock())
    granted, ttl = led.grant("v1:8080", "scrub", 1 << 20, 0.0)
    assert granted == 1 << 20 and ttl > 0


def test_ledger_budget_caps_grants(monkeypatch):
    clk = FakeClock()
    led = _ledger(monkeypatch, clk, 1.0)  # 1 MB/s cluster budget
    clk.advance(10)  # burst caps at 1s of budget = 1e6 bytes
    granted, _ = led.grant("v1:8080", "scrub", 10_000_000, 0.0)
    assert 0 < granted <= 1_000_000
    # drained: an immediate second ask gets (nearly) nothing
    granted2, _ = led.grant("v1:8080", "scrub", 10_000_000, 0.0)
    assert granted2 <= 1_000


def test_ledger_strict_priority_repair_over_scrub(monkeypatch):
    clk = FakeClock()
    led = _ledger(monkeypatch, clk, 1.0)
    clk.advance(10)
    # repair expresses demand for the WHOLE budget
    g_repair, _ = led.grant("v1:8080", "repair", 2_000_000, 0.0)
    assert g_repair > 0
    # scrub sees nothing while repair demand is in the window —
    # the budget it could take is reserved for the higher class
    clk.advance(1.0)  # 1e6 bytes refilled
    g_scrub, _ = led.grant("v2:8080", "scrub", 1_000_000, 0.0)
    assert g_scrub == 0
    # repair itself still drains the refill
    g_repair2, _ = led.grant("v1:8080", "repair", 2_000_000, 0.0)
    assert g_repair2 > 0
    # once repair demand ages out of the window, scrub is served again
    clk.advance(GrantLedger.DEMAND_WINDOW_S + 1.0)
    g_scrub2, _ = led.grant("v2:8080", "scrub", 500_000, 0.0)
    assert g_scrub2 > 0


def test_ledger_equal_rank_classes_share(monkeypatch):
    clk = FakeClock()
    led = _ledger(monkeypatch, clk, 1.0)
    clk.advance(10)
    # scrub and archival are the same rank: neither reserves against
    # the other, first-come-first-served from the shared bucket
    g1, _ = led.grant("v1:8080", "scrub", 400_000, 0.0)
    g2, _ = led.grant("v2:8080", "archival", 400_000, 0.0)
    assert g1 == 400_000 and g2 == 400_000


def test_ledger_unknown_class_and_pressure_report(monkeypatch):
    clk = FakeClock()
    led = _ledger(monkeypatch, clk, 1.0)
    granted, ttl = led.grant("v1:8080", "", 0, 0.73)
    assert granted == 0 and ttl > 0
    assert led.node_pressure("v1:8080") == pytest.approx(0.73)
    assert led.node_pressure("v9:8080") == 0.0
    st = led.status()
    assert st["servers"]["v1:8080"]["pressure"] == pytest.approx(0.73)


def test_ledger_stale_pressure_decays_to_zero(monkeypatch):
    led = _ledger(monkeypatch, FakeClock(), 0.0)
    led.grant("v1:8080", "", 0, 0.9)
    led.servers["v1:8080"]["unix"] = time.time() - 60
    assert led.node_pressure("v1:8080") == 0.0


# -- BackgroundGovernor: fail-open foreground / fail-closed background ------

class FakeVolumeServer:
    def __init__(self, qps: float = 0.0,
                 master: str = "localhost:1"):
        self.address = "fake:8080"
        self.master_grpc = master
        self._qps = qps
        self.pressure = 0.1

    def foreground_qps(self) -> float:
        return self._qps

    def qos_pressure(self) -> float:
        return self.pressure


def test_governor_noop_when_unconfigured(monkeypatch):
    monkeypatch.delenv("SWFS_QOS_BG_MBPS", raising=False)
    monkeypatch.delenv("SWFS_QOS_FG_QPS", raising=False)
    gov = BackgroundGovernor(FakeVolumeServer())
    assert not gov.enabled()
    # no master running anywhere — and none is needed
    assert gov.acquire("scrub", 1 << 30) == 0.0


def test_governor_fails_closed_on_unreachable_master(monkeypatch):
    monkeypatch.setenv("SWFS_QOS_BG_MBPS", "1")
    # nothing listens on port 1: the lease refresh must raise, not hang
    # and not silently grant
    gov = BackgroundGovernor(FakeVolumeServer(master="localhost:1"))
    with pytest.raises(QosUnavailable):
        gov.acquire("scrub", 1024, max_wait_s=0.1)


def test_governor_failpoint_fails_closed(monkeypatch):
    from seaweedfs_tpu.utils import failpoint

    monkeypatch.setenv("SWFS_QOS_BG_MBPS", "1")
    gov = BackgroundGovernor(FakeVolumeServer())
    with failpoint.active("qos.grant", mode="error", p=1.0):
        with pytest.raises(QosUnavailable):
            gov.acquire("archival", 1024, max_wait_s=0.1)


def test_background_never_starves_blocked_foreground(monkeypatch):
    """The inversion test: a background class stuck WAITING on the QoS
    plane must not block foreground writes. Foreground never calls into
    the governor (fail-open by construction), so while a scrub acquire
    is blocked mid-wait the foreground path must keep completing."""
    monkeypatch.setenv("SWFS_QOS_BG_MBPS", "1")
    srv = FakeVolumeServer()
    gov = BackgroundGovernor(srv)
    # a refresh that never grants: background waits its full budget
    gov._refresh = lambda klass, want: None
    done = threading.Event()
    err: list = []

    def background():
        try:
            gov.acquire("scrub", 1 << 20, max_wait_s=1.5)
        except QosUnavailable:
            pass
        except Exception as e:  # noqa: BLE001
            err.append(e)
        finally:
            done.set()

    t = threading.Thread(target=background, daemon=True)
    t.start()
    # while background is blocked, "foreground writes" (anything NOT
    # routed through the governor) proceed at full speed
    fg_completed = 0
    t0 = time.monotonic()
    while not done.is_set() and time.monotonic() - t0 < 10:
        srv.foreground_qps()  # the foreground path: no QoS gate at all
        fg_completed += 1
        if fg_completed > 50_000:
            break
    assert fg_completed > 10_000  # foreground never blocked
    t.join(timeout=10)
    assert done.is_set() and not err
    # and the starved background attempt was counted
    assert gov.denials >= 1


def test_governor_fg_qps_yield(monkeypatch):
    """The PR-4 backoff generalized: background yields while local
    foreground QPS exceeds the gate, resumes when it drops."""
    monkeypatch.delenv("SWFS_QOS_BG_MBPS", raising=False)
    monkeypatch.setenv("SWFS_QOS_FG_QPS", "10")
    monkeypatch.setenv("SWFS_QOS_FG_BACKOFF_MS", "10")
    srv = FakeVolumeServer(qps=100.0)
    gov = BackgroundGovernor(srv)

    def drop_soon():
        time.sleep(0.15)
        srv._qps = 0.0

    threading.Thread(target=drop_soon, daemon=True).start()
    waited = gov.acquire("scrub", 1024)
    assert waited >= 0.1  # yielded while foreground was hot


# -- pressure score ----------------------------------------------------------

def test_pressure_score_bounds_and_caps():
    assert pressure_score(0, 0) == 0.0
    assert pressure_score(10**9, 10**9) == 1.0
    # half-load on one axis only
    assert pressure_score(128, 0, gc_cap=256, dispatch_cap=64) == \
        pytest.approx(0.5)
    assert pressure_score(0, 32, gc_cap=256, dispatch_cap=64) == \
        pytest.approx(0.5)
    # negative depths clamp to idle
    assert pressure_score(-5, -5) == 0.0


def test_pressure_score_monotone_in_each_queue():
    """A rising queue can never LOWER the score — the property assign
    placement relies on to compare servers."""
    gc_grid = [0, 1, 8, 64, 128, 256, 300, 10_000]
    dp_grid = [0, 1, 4, 16, 32, 64, 100, 10_000]
    for dp in dp_grid:
        scores = [pressure_score(gc, dp, gc_cap=256, dispatch_cap=64)
                  for gc in gc_grid]
        assert scores == sorted(scores), f"non-monotone in gc at dp={dp}"
    for gc in gc_grid:
        scores = [pressure_score(gc, dp, gc_cap=256, dispatch_cap=64)
                  for dp in dp_grid]
        assert scores == sorted(scores), f"non-monotone in dp at gc={gc}"
    # strictly monotone while below both caps
    assert pressure_score(10, 10) < pressure_score(11, 10) \
        < pressure_score(11, 11)


def test_pressure_score_env_caps(monkeypatch):
    monkeypatch.setenv("SWFS_QOS_GC_CAP", "10")
    monkeypatch.setenv("SWFS_QOS_DISPATCH_CAP", "10")
    assert pressure_score(5, 0) == pytest.approx(0.5)
    assert pressure_score(10, 10) == 1.0


# -- placement folds pressure (topology-level) ------------------------------

def test_layout_pick_prefers_calm_replicas():
    from seaweedfs_tpu.storage.needle import TTL
    from seaweedfs_tpu.topology.topology import (
        DataNode,
        ReplicaPlacement,
        VolumeInfo,
        VolumeLayout,
    )

    rp = ReplicaPlacement.from_byte(0)
    vl = VolumeLayout(rp, TTL(), 1 << 30)
    nodes = []
    for i in range(3):
        dn = DataNode(ip="h", port=8080 + i, public_url=f"h:{8080+i}",
                      grpc_port=18080 + i, data_center="dc", rack="r")
        vi = VolumeInfo(id=i + 1, collection="", replica_placement=rp,
                        ttl=TTL(), version=3)
        vl.register(vi, dn)
        nodes.append(dn)
    # node 0 saturated, node 1 calm, node 2 middling — all fresh
    now = time.time()
    for dn, p in zip(nodes, (0.9, 0.0, 0.5)):
        dn.qos_pressure = p
        dn.qos_pressure_at = now
    picks = {vl.pick_for_write()[0] for _ in range(8)}
    assert picks == {2}  # volume 2 lives on the calm node
    # stale reports decay: with everyone stale it degrades to round-robin
    for dn in nodes:
        dn.qos_pressure_at = now - 3600
    picks = {vl.pick_for_write()[0] for _ in range(8)}
    assert picks == {1, 2, 3}
