"""Unit coverage for the ISSUE 2 small-file hot-path pieces:

- TieredChunkCache: disk-tier eviction, atime-scan LRU ordering, and the
  new write/delete invalidation semantics (write-overwrite-read must
  never return the old bytes);
- FidLeasePool: batching arithmetic ("fid_delta" minting), block
  expiry, invalidation, JWT degradation;
- Volume group commit: concurrent writers share flushes, acked bytes
  are OS-visible through fresh descriptors, idx is never ahead of dat,
  and the SWFS_GROUP_COMMIT=0 escape hatch restores flush-per-write;
- ssl.SSLError classification in utils/retry.is_retryable (ROADMAP
  open item): handshake/EOF flakes retry, certificate rejections fail
  fast — including when requests wraps them as ConnectionError.
"""

import os
import ssl
import threading
import time

import pytest

from seaweedfs_tpu.operation import AssignResult
from seaweedfs_tpu.storage.needle import Needle
from seaweedfs_tpu.storage.volume import Volume
from seaweedfs_tpu.utils import retry as retry_mod
from seaweedfs_tpu.utils.chunk_cache import DiskCache, TieredChunkCache
from seaweedfs_tpu.wdclient import lease as lease_mod
from seaweedfs_tpu.wdclient.lease import FidLeasePool


# -- TieredChunkCache ------------------------------------------------------

def test_chunk_cache_delete_invalidates_both_tiers(tmp_path):
    c = TieredChunkCache(mem_bytes=1 << 20, disk_dir=str(tmp_path),
                         disk_bytes=1 << 20, mem_threshold=1024)
    c.put("1,aa", b"x" * 10)        # memory tier
    c.put("2,bb", b"y" * 4096)      # disk tier
    assert c.get("1,aa") == b"x" * 10
    assert c.get("2,bb") == b"y" * 4096
    assert c.delete("1,aa") and c.delete("2,bb")
    assert c.get("1,aa") is None and c.get("2,bb") is None
    assert c.delete("1,aa") is False  # second delete: nothing left


def test_chunk_cache_overwrite_never_serves_old_bytes(tmp_path):
    """The filer protocol: an overwrite mints a NEW fid, caches the new
    bytes under it, and invalidates the old fid. After that sequence the
    old bytes must be unreachable through either key."""
    c = TieredChunkCache(mem_bytes=1 << 20, disk_dir=str(tmp_path),
                         disk_bytes=1 << 20, mem_threshold=64)
    c.put("3,old", b"version-1" * 20)   # disk tier (>64)
    c.put("3,old", b"v2")               # same fid re-written smaller: mem
    assert c.get("3,old") == b"v2", \
        "stale disk-tier bytes shadowed a newer same-fid write"
    c.put("4,new", b"version-2")
    c.delete("3,old")
    assert c.get("3,old") is None
    assert c.get("4,new") == b"version-2"


def test_chunk_cache_reput_routes_across_tiers(tmp_path):
    """A same-fid re-put that routes to the OTHER tier must evict the
    old entry there: mem is consulted first, so a stale mem entry would
    shadow a newer disk write forever (and vice versa on delete)."""
    c = TieredChunkCache(mem_bytes=1 << 20, disk_dir=str(tmp_path),
                         disk_bytes=1 << 20, mem_threshold=100)
    c.put("5,x", b"m" * 10)          # mem
    c.put("5,x", b"D" * 500)         # disk: mem copy must die
    assert c.get("5,x") == b"D" * 500, \
        "stale memory-tier entry shadowed a newer disk-tier write"
    c.put("5,x", b"m2" * 5)          # back to mem: disk copy must die
    assert c.get("5,x") == b"m2" * 5
    assert c.disk.get("5,x") is None


def test_disk_cache_eviction_is_atime_lru(tmp_path):
    dc = DiskCache(str(tmp_path), capacity_bytes=10_000)
    dc.put("a", b"A" * 3000)
    dc.put("b", b"B" * 3000)
    dc.put("c", b"C" * 3000)
    # age a + c, freshen b (atime drives the eviction scan)
    now = time.time()
    os.utime(dc._path("a"), (now - 300, now - 300))
    os.utime(dc._path("c"), (now - 200, now - 200))
    os.utime(dc._path("b"), (now, now))
    dc.put("d", b"D" * 3000)  # overflows: oldest-atime entries go first
    assert dc.get("a") is None, "LRU victim (oldest atime) survived"
    assert dc.get("b") == b"B" * 3000
    assert dc.get("d") == b"D" * 3000


def test_disk_cache_total_survives_delete_accounting(tmp_path):
    dc = DiskCache(str(tmp_path), capacity_bytes=8_000)
    dc.put("a", b"A" * 3000)
    assert dc.delete("a")
    # freed bytes must be reusable without eviction churn
    dc.put("b", b"B" * 3000)
    dc.put("c", b"C" * 3000)
    assert dc.get("b") and dc.get("c")


# -- FidLeasePool ----------------------------------------------------------

def _fake_assign(results):
    calls = []

    def assign(master, *, count=1, collection="", replication="", ttl="",
               data_center=""):
        calls.append(count)
        return results.pop(0)

    return assign, calls


def test_fid_lease_pool_mints_delta_fids(monkeypatch):
    a = AssignResult(fid="7,01aabbccdd", url="vs:8080", count=4)
    assign, calls = _fake_assign([a])
    monkeypatch.setattr(lease_mod, "assign", assign)
    pool = FidLeasePool("m:9333", batch=4)
    fids = [pool.acquire().fid for _ in range(4)]
    assert fids == ["7,01aabbccdd", "7,01aabbccdd_1",
                    "7,01aabbccdd_2", "7,01aabbccdd_3"]
    assert calls == [4], "four acquires must cost exactly one Assign"
    # "fid_delta" parses to base key + delta (ParsePath semantics)
    from seaweedfs_tpu.storage.file_id import parse_file_id
    f0, f3 = parse_file_id(fids[0]), parse_file_id(fids[3])
    assert f3.key == f0.key + 3 and f3.cookie == f0.cookie


def test_fid_lease_pool_expires_blocks(monkeypatch):
    results = [AssignResult(fid="7,01aa11223344", url="u", count=100),
               AssignResult(fid="8,01bb11223344", url="u", count=100)]
    assign, calls = _fake_assign(results)
    monkeypatch.setattr(lease_mod, "assign", assign)
    pool = FidLeasePool("m", batch=100, max_age=0.05)
    assert pool.acquire().fid.startswith("7,")
    time.sleep(0.08)
    assert pool.remaining() == 0, "expired block still counted"
    assert pool.acquire().fid.startswith("8,"), \
        "expired lease block was still handing out fids"
    assert calls == [100, 100]


def test_fid_lease_pool_invalidate_and_error_passthrough(monkeypatch):
    results = [AssignResult(fid="9,01cc11223344", url="u", count=8),
               AssignResult(error="no writable volumes")]
    assign, _ = _fake_assign(results)
    monkeypatch.setattr(lease_mod, "assign", assign)
    pool = FidLeasePool("m", batch=8)
    assert not pool.acquire().error
    assert pool.remaining() == 7
    pool.invalidate()
    assert pool.remaining() == 0
    assert pool.acquire().error == "no writable volumes"


def test_fid_lease_pool_jwt_blocks_never_batch(monkeypatch):
    """The master signs the BASE fid only: an auth'd assign must not
    stock delta fids that would fail JWT verification."""
    results = [AssignResult(fid="5,01dd11223344", url="u", count=16,
                            auth="jwt-token"),
               AssignResult(fid="5,01ee11223344", url="u", count=1,
                            auth="jwt-token")]
    assign, calls = _fake_assign(results)
    monkeypatch.setattr(lease_mod, "assign", assign)
    pool = FidLeasePool("m", batch=16)
    first = pool.acquire()
    assert first.auth and "_" not in first.fid
    assert pool.remaining() == 0
    assert "_" not in pool.acquire().fid
    # the pool LEARNS: after the first signed reply, it stops reserving
    # whole blocks of needle ids it can never hand out
    assert calls == [16, 1]


def test_fid_lease_refill_racing_invalidate_is_discarded(monkeypatch):
    """A refill Assign completing AFTER invalidate() must not stock its
    (suspect) block — otherwise save_chunk's single retry draws a fid
    from the very volume whose failure triggered the invalidation."""
    pool = FidLeasePool("m", batch=8)

    def racing_assign(master, *, count=1, **kw):
        pool.invalidate()  # lands while this RPC is "in flight"
        return AssignResult(fid="3,01aa11223344", url="u", count=count)

    monkeypatch.setattr(lease_mod, "assign", racing_assign)
    a = pool.acquire()
    assert not a.error
    assert pool.remaining() == 0, \
        "stale refilled block survived a concurrent invalidate"


def test_fid_lease_pool_separate_keys(monkeypatch):
    results = [AssignResult(fid="1,01aa11223344", url="u", count=10),
               AssignResult(fid="2,01bb11223344", url="u", count=10)]
    assign, calls = _fake_assign(results)
    monkeypatch.setattr(lease_mod, "assign", assign)
    pool = FidLeasePool("m", batch=10)
    a = pool.acquire(collection="hot")
    b = pool.acquire(collection="cold")
    assert a.fid.startswith("1,") and b.fid.startswith("2,")
    assert pool.acquire(collection="hot").fid.startswith("1,")
    assert len(calls) == 2
    # invalidation is per-key: one failing collection must not destroy
    # the other's healthy batching
    pool.invalidate(collection="hot")
    assert pool.remaining() == 9  # cold's block survives (10 - 1 taken)
    assert pool.acquire(collection="cold").fid.startswith("2,")
    assert len(calls) == 2, "cold re-assigned despite a live lease"


# -- volume group commit ---------------------------------------------------

def _mk_volume(tmp_path, vid=1):
    return Volume(str(tmp_path), "", vid)


def test_group_commit_concurrent_writers_share_flushes(tmp_path):
    from seaweedfs_tpu.utils.stats import (
        VOLUME_GROUP_COMMIT_FLUSHES,
        VOLUME_GROUP_COMMIT_WRITES,
    )

    v = _mk_volume(tmp_path)
    w0 = VOLUME_GROUP_COMMIT_WRITES.value()
    f0 = VOLUME_GROUP_COMMIT_FLUSHES.value()
    n_threads, per = 8, 25
    errs = []

    def writer(t):
        try:
            for i in range(per):
                nid = t * 1000 + i + 1
                n = Needle.create(nid, 0x1234, b"gc" * 40 + bytes([t, i]))
                v.write_needle(n)
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    threads = [threading.Thread(target=writer, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    writes = VOLUME_GROUP_COMMIT_WRITES.value() - w0
    flushes = VOLUME_GROUP_COMMIT_FLUSHES.value() - f0
    assert writes == n_threads * per
    assert 0 < flushes <= writes
    # every acked write is OS-visible through a FRESH descriptor
    base = v.file_name()
    with open(base + ".dat", "rb") as f:
        raw = f.read()
    for t in range(n_threads):
        for i in range(per):
            assert (b"gc" * 40 + bytes([t, i])) in raw
    # idx on disk never ahead of dat: every idx entry parses to a
    # record that exists within the dat bytes already on the OS
    from seaweedfs_tpu.storage import idx as idx_mod, types
    ids, offs, sizes = idx_mod.read_index_file(base + ".idx")
    for off, size in zip(offs, sizes):
        end = types.stored_to_actual_offset(int(off)) + \
            types.actual_size(int(size), v.version)
        assert end <= len(raw), "idx entry points past the durable dat"
    v.close()


def test_group_commit_read_your_own_write(tmp_path):
    v = _mk_volume(tmp_path)
    n = Needle.create(42, 0xabcd, b"read-back")
    v.write_needle(n)
    got = v.read_needle(42, 0xabcd)
    assert got.data == b"read-back"
    # overwrite + delete keep working through the deferred-flush path
    v.write_needle(Needle.create(42, 0xabcd, b"read-back-2"))
    assert v.read_needle(42, 0xabcd).data == b"read-back-2"
    assert v.delete_needle(42, 0xabcd) > 0
    v.close()
    # a fresh Volume replays the idx: the acked state survives
    v2 = _mk_volume(tmp_path)
    from seaweedfs_tpu.storage.errors import NotFoundError
    with pytest.raises(NotFoundError):
        v2.read_needle(42, 0xabcd)
    v2.close()


def test_group_commit_env_escape_hatch(tmp_path, monkeypatch):
    monkeypatch.setenv("SWFS_GROUP_COMMIT", "0")
    v = _mk_volume(tmp_path, vid=3)
    assert v._gc_enabled is False
    assert v.nm.auto_flush is True
    v.write_needle(Needle.create(7, 1, b"inline-flush"))
    base = v.file_name()
    with open(base + ".dat", "rb") as f:
        assert b"inline-flush" in f.read()
    v.close()


def test_group_commit_flush_failure_freezes_volume(tmp_path):
    """A failed batch flush must not let a LATER write's flush silently
    commit bytes whose writer was told 500: the volume freezes for
    writes (restart repair converges on the durable prefix). The freeze
    flag is independent of read_only, so it can never clobber a
    read-only state set by an admin/EC path meanwhile."""
    v = _mk_volume(tmp_path, vid=5)
    v.write_needle(Needle.create(1, 1, b"pre-failure"))
    real_flush = v._dat.flush
    def boom():
        raise OSError(28, "No space left on device")
    v._dat.flush = boom
    with pytest.raises(IOError):
        v.write_needle(Needle.create(2, 2, b"doomed"))
    assert v._gc_frozen
    assert not v.read_only  # the admin flag stays untouched
    v._dat.flush = real_flush
    with pytest.raises(IOError):  # frozen: new writes are refused
        v.write_needle(Needle.create(3, 3, b"rejected"))
    v.close()


def test_filer_cache_skips_ttl_and_serves_cacheable(tmp_path):
    """_read_chunk_view rung 0: cacheable views are served from the
    fid-keyed cache with zero volume round-trips; non-cacheable (TTL'd)
    views bypass the cache entirely (nothing would ever expire them)."""
    from seaweedfs_tpu.filer.filechunks import ChunkView
    from seaweedfs_tpu.server.filer import FilerServer

    srv = FilerServer(ip="localhost", port=18888, master="localhost:1",
                      store_dir=str(tmp_path))  # never started
    try:
        assert srv.chunk_cache is not None
        srv.chunk_cache.put("9,aabbccdd11", b"cached-bytes")
        view = ChunkView(file_id="9,aabbccdd11", chunk_offset=0,
                         size=len(b"cached-bytes"), logical_offset=0,
                         is_full_chunk=True)
        assert srv._read_chunk_view(view) == b"cached-bytes"
        # TTL'd entry: the cache must not answer — the (dead) cluster is
        # consulted and the read fails instead of serving expired bytes
        srv.master_client.lookup_file_id = \
            lambda fid, refresh=False: (_ for _ in ()).throw(
                LookupError("volume gone"))
        srv.master_client.ec_fallback_urls = lambda fid: []
        with pytest.raises(IOError):
            srv._read_chunk_view(view, cacheable=False)
    finally:
        srv.filer.store.close()


def test_filer_disk_only_cache_config(tmp_path, monkeypatch):
    from seaweedfs_tpu.server.filer import FilerServer

    monkeypatch.setenv("SWFS_FILER_CACHE_MB", "0")
    monkeypatch.setenv("SWFS_FILER_CACHE_DISK_MB", "32")
    srv = FilerServer(ip="localhost", port=18889, master="localhost:1",
                      store_dir=str(tmp_path))
    try:
        assert srv.chunk_cache is not None, \
            "disk-only cache config was silently dropped"
        srv.chunk_cache.put("1,smallchunk99", b"tiny")  # routes to disk
        assert srv.chunk_cache.get("1,smallchunk99") == b"tiny"
        assert srv.chunk_cache.disk is not None
        assert srv.chunk_cache.disk.get("1,smallchunk99") == b"tiny"
    finally:
        srv.filer.store.close()


# -- ssl.SSLError classification (ROADMAP open item) -----------------------

def test_ssl_cert_verification_fails_fast():
    e = ssl.SSLCertVerificationError(
        1, "certificate verify failed: unable to get local issuer")
    assert retry_mod.is_retryable(e) is False


def test_ssl_handshake_flakes_retry():
    assert retry_mod.is_retryable(ssl.SSLEOFError(
        8, "EOF occurred in violation of protocol")) is True
    assert retry_mod.is_retryable(ssl.SSLWantReadError()) is True
    generic = ssl.SSLError(1, "[SSL] record layer failure")
    assert retry_mod.is_retryable(generic) is True


def test_ssl_generic_cert_reason_fails_fast():
    e = ssl.SSLError(1, "alert")
    e.reason = "TLSV1_ALERT_UNKNOWN_CA"
    assert retry_mod.is_retryable(e) is False
    e2 = ssl.SSLError(1, "sslv3 alert certificate expired")
    e2.reason = "SSLV3_ALERT_CERTIFICATE_EXPIRED"
    assert retry_mod.is_retryable(e2) is False


def test_ssl_wrapped_in_requests_connectionerror():
    """requests.exceptions.SSLError subclasses ConnectionError — without
    the unwrap, cert rejections would ride the blanket retry branch."""
    import requests as rq

    inner = ssl.SSLCertVerificationError(1, "certificate verify failed")
    wrapped = rq.exceptions.SSLError(inner)
    assert retry_mod.is_retryable(wrapped) is False
    flaky = rq.exceptions.SSLError(
        ssl.SSLEOFError(8, "EOF occurred in violation of protocol"))
    assert retry_mod.is_retryable(flaky) is True
    # plain connection refusals keep retrying as before
    assert retry_mod.is_retryable(rq.exceptions.ConnectionError()) is True
