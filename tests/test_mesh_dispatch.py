"""Multi-chip sharded EC dispatch (ISSUE 5): V-axis lanes with
device-affine flushing.

The load-bearing property is the same as ISSUE 3's: per-chip lanes are
allowed to change only WHERE dispatches run, never what they compute —
V-axis bit-identity is pinned against the single-chip scheduler path,
the rs_cpu oracle (and its vsharded mirror), and the frozen golden shard
hashes. On top of that: per-chip lane fairness under 8 concurrent
pipelines (no chip starves), survivor-set chip placement with LRU
eviction, demand-flush latency through a device-affine lane, and clean
shutdown with in-flight per-chip dispatches.

Runs on the forced 8-device host platform (tests/conftest.py sets
--xla_force_host_platform_device_count=8).
"""

import hashlib
import os
import threading

import numpy as np
import pytest

from seaweedfs_tpu.models.coder import new_coder
from seaweedfs_tpu.ops import dispatch
from seaweedfs_tpu.ops.rs_cpu import RSCodecCPU
from seaweedfs_tpu.parallel.mesh import ShardedCoder, device_count
from seaweedfs_tpu.storage import ec_files
from seaweedfs_tpu.storage.ec_locate import Geometry
from seaweedfs_tpu.utils import stats

TEST_GEO = Geometry(large_block=10000, small_block=100)


@pytest.fixture(autouse=True)
def _clean_schedulers():
    yield
    dispatch.shutdown_all()
    assert not [t for t in threading.enumerate()
                if t.name.startswith("ec-dispatch") and t.is_alive()], \
        "leaked ec-dispatch flusher thread"


def _mesh_coder():
    if device_count() < 2:
        pytest.skip("needs the forced multi-device host platform")
    return ShardedCoder(10, 4)


# -- V-axis shard_map variants: bit-identity --------------------------------


@pytest.mark.parametrize("v", [8, 11, 16, 3])
def test_vsharded_encode_stacked_bit_identity(v):
    """encode_parity_stacked with the V axis sharded across chips (v >=
    chips; v=3 exercises the column-split fallback) == per-slab rs_cpu,
    and == the CPU mirror of the exact per-chip partitioning."""
    coder = _mesh_coder()
    cpu = RSCodecCPU(10, 4)
    rng = np.random.default_rng(31)
    stack = rng.integers(0, 256, (v, 10, 257), dtype=np.uint8)
    got = np.asarray(coder.encode_parity_stacked(stack))
    want = np.stack([np.asarray(cpu.encode_parity(s)) for s in stack])
    assert got.shape == (v, 4, 257)
    assert np.array_equal(got, want)
    mirror = cpu.encode_parity_stacked_vsharded(stack, coder._n)
    assert np.array_equal(mirror, want)


def test_vsharded_encode_ragged_widths_zero_padding():
    """Ragged slab tails ride zero-padded columns through the V-sharded
    launch exactly as they do through the column split."""
    coder = _mesh_coder()
    cpu = RSCodecCPU(10, 4)
    rng = np.random.default_rng(32)
    widths = [512, 100, 37, 512, 9, 300, 64, 200, 411]
    bmax = max(widths)
    stack = np.zeros((len(widths), 10, bmax), dtype=np.uint8)
    slabs = []
    for i, w in enumerate(widths):
        s = rng.integers(0, 256, (10, w), dtype=np.uint8)
        stack[i, :, :w] = s
        slabs.append(s)
    out = np.asarray(coder.encode_parity_stacked(stack))
    for i, (w, s) in enumerate(zip(widths, slabs)):
        assert np.array_equal(out[i][:, :w],
                              np.asarray(cpu.encode_parity(s))), i
        assert not out[i][:, w:].any(), "zero columns must encode to zero"


@pytest.mark.parametrize("data_only", [False, True])
def test_vsharded_reconstruct_survivor_permutations(data_only):
    coder = _mesh_coder()
    cpu = RSCodecCPU(10, 4)
    rng = np.random.default_rng(33)
    data = rng.integers(0, 256, (10, 130), dtype=np.uint8)
    shards = np.asarray(cpu.encode(
        np.vstack([data, np.zeros((4, 130), np.uint8)])))
    for _ in range(4):
        ids = list(range(14))
        rng.shuffle(ids)
        pres = tuple(ids[:11])
        stk = np.stack([shards[i] for i in pres])
        vstack = np.stack([stk] * 9)  # ragged V vs the 8-device mesh
        m, rows = coder.reconstruct_stacked_vsharded(
            pres, vstack, data_only=data_only)
        m2, r2 = cpu.reconstruct_stacked(pres, stk, data_only=data_only)
        rows = np.asarray(rows)
        assert tuple(m) == tuple(m2)
        for j in range(9):
            assert np.array_equal(rows[j], np.asarray(r2)), j


def test_golden_shard_hashes_mesh_vsharded():
    """The frozen RS(10,4) fixture's shard bytes survive the V-sharded
    path (same golden as test_golden_identity pins for cpu/jax)."""
    import sys

    sys.path.insert(0, os.path.dirname(__file__))
    from test_golden_identity import GOLDEN_SHARD_SHA256, _fixture

    coder = _mesh_coder()
    data = _fixture()
    stack = np.stack([data] * coder._n)  # every chip encodes the fixture
    parity = np.asarray(coder.encode_parity_stacked(stack))
    for slab in parity:
        shards = np.concatenate([data, slab], axis=0)
        got = [hashlib.sha256(s.tobytes()).hexdigest() for s in shards]
        assert got == GOLDEN_SHARD_SHA256


# -- scheduler: per-chip lanes ----------------------------------------------


def test_scheduler_per_chip_encode_bit_identity_and_spread():
    """Slabs submitted through the scheduler round-robin over per-chip
    lanes; every future's bytes match the rs_cpu oracle and every chip
    issued at least one batch."""
    coder = _mesh_coder()
    cpu = RSCodecCPU(10, 4)
    sched = dispatch.EcDispatchScheduler(coder, window=0.05)
    try:
        rng = np.random.default_rng(34)
        b0 = stats.EC_DISPATCH_BATCHES.split_by("chip", lane="encode")
        slabs = [rng.integers(0, 256, (10, 64 + 8 * i), dtype=np.uint8)
                 for i in range(3 * coder._n)]
        futs = [sched.encode_parity(s) for s in slabs]
        for s, f in zip(slabs, futs):
            assert np.array_equal(np.asarray(f),
                                  np.asarray(cpu.encode_parity(s)))
        b1 = stats.EC_DISPATCH_BATCHES.split_by("chip", lane="encode")
        moved = {c: b1.get(c, 0) - b0.get(c, 0) for c in b1}
        for c in range(coder._n):
            assert moved.get(str(c), 0) > 0, f"chip {c} starved: {moved}"
    finally:
        sched.close()


def test_scheduler_vshard_env_gate_single_funnel():
    """SWFS_EC_DISPATCH_VSHARD=0 restores ISSUE 3's single stacked
    funnel: no per-chip lanes, bytes unchanged."""
    coder = _mesh_coder()
    cpu = RSCodecCPU(10, 4)
    os.environ["SWFS_EC_DISPATCH_VSHARD"] = "0"
    try:
        sched = dispatch.EcDispatchScheduler(coder, window=0.05)
        b0 = stats.EC_DISPATCH_BATCHES.split_by("chip", lane="encode")
        rng = np.random.default_rng(35)
        slabs = [rng.integers(0, 256, (10, 96), dtype=np.uint8)
                 for _ in range(12)]
        futs = [sched.encode_parity(s) for s in slabs]
        for s, f in zip(slabs, futs):
            assert np.array_equal(np.asarray(f),
                                  np.asarray(cpu.encode_parity(s)))
        b1 = stats.EC_DISPATCH_BATCHES.split_by("chip", lane="encode")
        assert b1.get("-", 0) > b0.get("-", 0), "single-funnel lane unused"
        assert all(b1.get(str(c), 0) == b0.get(str(c), 0)
                   for c in range(coder._n)), "chip lanes used while gated"
        sched.close()
    finally:
        os.environ.pop("SWFS_EC_DISPATCH_VSHARD", None)


def test_per_chip_lane_fairness_under_8_pipelines():
    """8 concurrent encode pipelines (one thread each, as 8 volumes
    encoding at once): every chip's dispatch counter moves — the fleet
    saturates every chip's queue instead of funnelling through one."""
    coder = _mesh_coder()
    cpu = RSCodecCPU(10, 4)
    sched = dispatch.EcDispatchScheduler(coder, window=0.02)
    try:
        rng = np.random.default_rng(36)
        payloads = [
            [rng.integers(0, 256, (10, 128), dtype=np.uint8)
             for _ in range(6)]
            for _ in range(8)
        ]
        want = [[np.asarray(cpu.encode_parity(s)) for s in lane]
                for lane in payloads]
        b0 = stats.EC_DISPATCH_BATCHES.split_by("chip", lane="encode")
        errs = []
        barrier = threading.Barrier(8)

        def pipeline(i):
            try:
                barrier.wait()
                futs = [sched.encode_parity(s) for s in payloads[i]]
                for w, f in zip(want[i], futs):
                    assert np.array_equal(np.asarray(f), w)
            except BaseException as e:  # noqa: BLE001
                errs.append(e)

        ths = [threading.Thread(target=pipeline, args=(i,))
               for i in range(8)]
        for t in ths:
            t.start()
        for t in ths:
            t.join()
        assert not errs, errs[0]
        b1 = stats.EC_DISPATCH_BATCHES.split_by("chip", lane="encode")
        for c in range(coder._n):
            assert b1.get(str(c), 0) > b0.get(str(c), 0), \
                f"chip {c} starved under the 8-pipeline load"
    finally:
        sched.close()


def test_reconstruct_survivor_set_chip_placement_lru():
    """Each survivor set is pinned to one chip (its fused decode matrix
    lives there); distinct sets spread over distinct chips; the
    assignment map is LRU-bounded."""
    coder = _mesh_coder()
    cpu = RSCodecCPU(10, 4)
    sched = dispatch.EcDispatchScheduler(coder, window=0.02)
    sched._rec_max = 4
    try:
        rng = np.random.default_rng(37)
        data = rng.integers(0, 256, (10, 96), dtype=np.uint8)
        shards = np.asarray(cpu.encode(
            np.vstack([data, np.zeros((4, 96), np.uint8)])))
        seen_chips = set()
        keys = []
        for drop in range(6):  # 6 distinct survivor sets > LRU cap 4
            pres = tuple(i for i in range(14)
                         if i not in (drop, drop + 4, drop + 8))[:11]
            stk = np.stack([shards[i] for i in pres])
            m, rows = sched.reconstruct_stacked(pres, stk).result()
            m2, r2 = cpu.reconstruct_stacked(pres, stk)
            assert tuple(m) == tuple(m2)
            assert np.array_equal(np.asarray(rows), np.asarray(r2))
            key = ("rec", sched.geom_id, pres, False, None)
            keys.append(key)
            with sched._cv:
                chip = sched._rec_chips.get(key)
            assert chip is not None
            seen_chips.add(chip)
        assert len(seen_chips) > 1, "survivor sets all pinned to one chip"
        with sched._cv:
            assert len(sched._rec_chips) <= 4, "rec-chip map not LRU-bounded"
            assert keys[0] not in sched._rec_chips, "oldest set not evicted"
        # a re-used (re-assigned) set still reconstructs bit-identically
        pres = keys[0][2]
        stk = np.stack([shards[i] for i in pres])
        m, rows = sched.reconstruct_stacked(pres, stk).result()
        m2, r2 = cpu.reconstruct_stacked(pres, stk)
        assert tuple(m) == tuple(m2)
        assert np.array_equal(np.asarray(rows), np.asarray(r2))
    finally:
        sched.close()


def test_big_uniform_reconstruct_batch_vshards_across_mesh():
    """A reconstruct lane whose demand-flushed backlog holds >= chips
    equal-width slabs (a rebuild pipeline's shape) dispatches through
    the V-sharded mesh variant instead of its single assigned chip —
    bytes identical slab for slab."""
    coder = _mesh_coder()
    cpu = RSCodecCPU(10, 4)
    sched = dispatch.EcDispatchScheduler(coder, window=30.0,
                                         max_slabs=64)
    try:
        rng = np.random.default_rng(39)
        data = rng.integers(0, 256, (10, 128), dtype=np.uint8)
        shards = np.asarray(cpu.encode(
            np.vstack([data, np.zeros((4, 128), np.uint8)])))
        pres = tuple(i for i in range(14) if i not in (1, 6, 12))
        stk = np.stack([shards[i] for i in pres])
        want = cpu.reconstruct_stacked(pres, stk)
        futs = [sched.reconstruct_stacked(pres, stk, copy=True)
                for _ in range(2 * coder._n)]  # > chips, uniform width
        # first result() demand-flushes the whole lane as ONE batch
        for f in futs:
            m, rows = f.result(timeout=30)
            assert tuple(m) == tuple(want[0])
            assert np.array_equal(np.asarray(rows), np.asarray(want[1]))
    finally:
        sched.close()


def test_demand_flush_latency_with_device_affine_lanes():
    """A consumer blocked on a per-chip lane demand-flushes THAT lane
    immediately — a 30s window never becomes serving latency."""
    import time

    coder = _mesh_coder()
    cpu = RSCodecCPU(10, 4)
    sched = dispatch.EcDispatchScheduler(coder, window=30.0)
    try:
        data = np.arange(640, dtype=np.uint8).reshape(10, 64)
        t0 = time.perf_counter()
        fut = sched.encode_parity(data)
        out = np.asarray(fut.result(timeout=10))
        assert time.perf_counter() - t0 < 5.0
        assert np.array_equal(out, np.asarray(cpu.encode_parity(data)))
    finally:
        sched.close()


def test_clean_shutdown_with_inflight_per_chip_dispatches():
    """close() with slabs queued across several chip lanes resolves every
    future (drain-then-join) and rejects new work afterwards."""
    coder = _mesh_coder()
    cpu = RSCodecCPU(10, 4)
    sched = dispatch.EcDispatchScheduler(coder, window=30.0)  # never fires
    rng = np.random.default_rng(38)
    slabs = [rng.integers(0, 256, (10, 80), dtype=np.uint8)
             for _ in range(2 * coder._n)]
    futs = [sched.encode_parity(s) for s in slabs]
    assert sched.pending() == len(slabs)
    depths = sched.chip_depths()
    assert sum(depths.values()) == len(slabs)
    assert len([c for c in depths if c != "-"]) == coder._n
    sched.close()
    for s, f in zip(slabs, futs):
        assert f.done()
        assert np.array_equal(np.asarray(f.result(timeout=1)),
                              np.asarray(cpu.encode_parity(s)))
    with pytest.raises(RuntimeError):
        sched.encode_parity(np.zeros((10, 8), np.uint8))
    sched.close()  # idempotent


def test_shutdown_all_idempotent():
    """shutdown_all twice (as atexit + Store.close teardown orders can
    produce) is a no-op the second time, and a broken scheduler in the
    set cannot stop the others from closing. (atexit registration itself
    happens at module import — ops/dispatch.py — and is not portably
    introspectable; idempotency is the property it depends on.)"""
    coder = RSCodecCPU(10, 4)
    sched = dispatch.scheduler_for(coder)
    np.asarray(sched.encode_parity(np.zeros((10, 16), np.uint8)))
    dispatch.shutdown_all()
    dispatch.shutdown_all()  # second call is a no-op, not an error
    assert sched.closed

    class _Broken(dispatch.EcDispatchScheduler):
        def close(self):
            raise RuntimeError("teardown bomb")

    boom = _Broken(RSCodecCPU(10, 4), window=0.01)
    healthy = dispatch.EcDispatchScheduler(RSCodecCPU(10, 4), window=0.01)
    dispatch.shutdown_all()  # must visit every scheduler despite the bomb
    assert healthy.closed
    dispatch.EcDispatchScheduler.close(boom)  # real cleanup


# -- pipeline golden safety over the mesh -----------------------------------


def test_generate_ec_files_bit_identical_vshard_on_off(tmp_path, monkeypatch):
    """The acceptance pin: .ec00-.ec13 bytes identical with per-chip
    lanes on and off, over the mesh-backed auto coder."""
    import sys

    sys.path.insert(0, os.path.dirname(__file__))
    from test_ec_pipeline import _make_synthetic_volume

    outs = {}
    for mode in ("0", "1"):
        monkeypatch.setenv("SWFS_EC_DISPATCH_VSHARD", mode)
        monkeypatch.setenv("SWFS_EC_MESH_VSHARD", mode)
        base = str(tmp_path / f"v{mode}")
        _make_synthetic_volume(base, seed=41)
        coder = new_coder(10, 4, "tpu")
        ec_files.generate_ec_files(base, coder, TEST_GEO, batch_size=50)
        dispatch.shutdown_all()
        outs[mode] = [
            open(TEST_GEO.shard_file_name(base, i), "rb").read()
            for i in range(14)
        ]
    for i in range(14):
        assert outs["0"][i] == outs["1"][i], f"shard {i} differs"


def test_store_close_twice_is_safe(tmp_path):
    """Satellite: Store.close() is idempotent — a double close neither
    re-closes volumes nor re-joins the dispatch flusher."""
    from seaweedfs_tpu.storage.needle import Needle
    from seaweedfs_tpu.storage.store import Store

    st = Store([str(tmp_path)])
    v = st.add_volume(1)
    v.write_needle(Needle.create(1, 0xA, b"x" * 100))
    # attach a scheduler (as EC work would) so close exercises the join
    sched = dispatch.scheduler_for(st.coder)
    np.asarray(sched.encode_parity(np.zeros((10, 16), np.uint8)))
    st.close()
    st.close()  # must not hang or raise
    assert sched.closed
