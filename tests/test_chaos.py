"""Chaos suite: failpoint-driven fault injection through the real server
stacks (utils/failpoint.py + utils/retry.py — ISSUE 1 tentpole).

Every scenario arms a named failpoint and then drives the ordinary
client paths, asserting ZERO client-visible errors while the injected
faults demonstrably fire (`hits` assertions):

- replica loss: `volume.http.read` fails 20%/100% of reads on ONE
  replica; filer reads fail over to the survivor
- EC degradation: `ec.shard.read` loses four data shards; reads
  reconstruct from the remaining k
- master outage: `pb.Assign` flaps the leader mid-assign; a raft trio
  loses its real leader and assign follows the new one
- metadata-backend flaps: `filer.store.mutate` interrupts store writes;
  RetryingStore absorbs them
- replication sink flaps: `replication.sink` bounces applies; the
  Replicator retries instead of dropping events
- subprocess stacks: SWFS_FAILPOINTS env arms a spawned `weed server`

The volume-data-plane scenarios need the Python HTTP handlers (that's
where the failpoints live), so the fixture pins SEAWEEDFS_TPU_NATIVE=0
while the cluster is up.
"""

import os
import socket
import subprocess
import sys
import time

import numpy as np
import pytest
import requests

from seaweedfs_tpu.operation import assign, submit
from seaweedfs_tpu.pb import filer_pb2, master_pb2, rpc
from seaweedfs_tpu.pb import volume_server_pb2 as vs
from seaweedfs_tpu.replication import LocalSink, Replicator
from seaweedfs_tpu.server.filer import FilerServer
from seaweedfs_tpu.server.master import MasterServer
from seaweedfs_tpu.server.volume import VolumeServer
from seaweedfs_tpu.storage.ec_locate import Geometry
from seaweedfs_tpu.storage.file_id import parse_file_id
from seaweedfs_tpu.utils import failpoint
from seaweedfs_tpu.wdclient import MasterClient

pytestmark = pytest.mark.chaos

TEST_GEO = Geometry(large_block=10000, small_block=100)


def _free_port() -> int:
    """A free HTTP port whose +10000 gRPC sibling is also free — servers
    derive their gRPC listener from the HTTP port, so probing only one
    of the pair invites bind collisions across the suite."""
    for _ in range(50):
        with socket.socket() as s:
            s.bind(("", 0))
            port = s.getsockname()[1]
        if port + 10000 > 65535:
            continue
        with socket.socket() as s2:
            try:
                s2.bind(("", port + 10000))
            except OSError:
                continue
        return port
    raise RuntimeError("no free port pair found")


@pytest.fixture(autouse=True)
def _no_leaked_failpoints():
    failpoint.clear()
    yield
    failpoint.clear()


@pytest.fixture(scope="module")
def chaos_cluster(tmp_path_factory):
    """master + 2 volume servers (replication 001) + filer."""
    old_native = os.environ.get("SEAWEEDFS_TPU_NATIVE")
    os.environ["SEAWEEDFS_TPU_NATIVE"] = "0"
    tmp = tmp_path_factory.mktemp("chaos")
    mport = _free_port()
    master = MasterServer(ip="localhost", port=mport,
                          volume_size_limit_mb=64)
    master.start(vacuum_interval=3600)
    volumes = []
    for i in range(2):
        vsrv = VolumeServer(
            directories=[str(tmp / f"vol{i}")],
            master=f"localhost:{mport}", ip="localhost",
            port=_free_port(), pulse_seconds=1, ec_geometry=TEST_GEO,
            # every test in this module grows volumes (replication 001
            # doubles them) and mounted EC shards count against slots
            # too — the default 8 per store runs out before the end
            max_volume_counts=[64])
        vsrv.start()
        volumes.append(vsrv)
    fsrv = FilerServer(ip="localhost", port=_free_port(),
                       master=f"localhost:{mport}",
                       store_dir=str(tmp / "filer"),
                       chunk_size=32 * 1024, replication="001")
    fsrv.start()
    deadline = time.time() + 10
    while time.time() < deadline and len(master.topo.nodes) < 2:
        time.sleep(0.05)
    assert len(master.topo.nodes) == 2, "volume servers did not register"
    yield master, volumes, fsrv
    fsrv.stop()
    for v in volumes:
        v.stop()
    master.stop()
    rpc.reset_channels()
    if old_native is None:
        os.environ.pop("SEAWEEDFS_TPU_NATIVE", None)
    else:
        os.environ["SEAWEEDFS_TPU_NATIVE"] = old_native


# -- volume plane: replica failover ----------------------------------------

def _put_replicated(fsrv, base, path, payload, attempts=5):
    """PUT `payload` and prove every chunk is readable from BOTH
    replicas before the test arms failpoints. The lease-pooled PUT
    returns fast enough that the master may not have absorbed the second
    server's heartbeat for a freshly-grown volume yet — the write then
    lands un-replicated and the filer caches a one-location map for 10
    minutes, starving the targeted replica of reads and making the
    failpoint hits-assertions vacuously fail. A re-PUT after the
    locations registered replicates properly (fresh fids)."""
    for _ in range(attempts):
        r = requests.put(base + path, data=payload, timeout=30)
        assert r.status_code in (200, 201), r.text
        fids = [c.file_id for c in fsrv.filer.find_entry(path).chunks]
        deadline = time.time() + 10
        while time.time() < deadline:
            try:
                locs = {v: fsrv.master_client.lookup_volume(
                            v, refresh=True)
                        for v in {parse_file_id(f).volume_id
                                  for f in fids}}
            except LookupError:
                time.sleep(0.2)
                continue
            if all(len(l) >= 2 for l in locs.values()) and all(
                    requests.get(f"http://{l.url}/{fid}",
                                 timeout=10).status_code == 200
                    for fid in fids
                    for l in locs[parse_file_id(fid).volume_id]):
                return
            time.sleep(0.2)
    raise AssertionError(f"{path} never landed on both replicas")


@pytest.fixture
def no_filer_cache(chaos_cluster):
    """Bypass the filer chunk cache: replica-failover scenarios must
    drive every read down to the volume servers, where the failpoints
    live (a cache hit would make the chaos vacuous, not survived)."""
    _, _, fsrv = chaos_cluster
    saved = fsrv.chunk_cache
    fsrv.chunk_cache = None
    yield
    fsrv.chunk_cache = saved


def test_filer_read_survives_flaky_replica(chaos_cluster, no_filer_cache):
    """20% of reads on one replica fail; every filer read still returns
    the right bytes (acceptance scenario #1)."""
    master, volumes, fsrv = chaos_cluster
    payload = np.random.default_rng(1).integers(
        0, 256, size=150_000, dtype=np.uint8).tobytes()
    base = f"http://{fsrv.address}"
    _put_replicated(fsrv, base, "/chaos/flaky.bin", payload)
    with failpoint.active("volume.http.read", p=0.2, seed=7,
                          match=volumes[0].address + ",") as fp:
        for _ in range(25):
            got = requests.get(f"{base}/chaos/flaky.bin", timeout=30)
            assert got.status_code == 200
            assert got.content == payload
        assert fp.hits > 0, "chaos never fired — test is vacuous"


def test_filer_read_survives_dead_replica(chaos_cluster, no_filer_cache):
    """One replica 100% dead for reads: still zero client-visible
    errors via the surviving replica."""
    master, volumes, fsrv = chaos_cluster
    payload = b"replica-down " * 4000
    base = f"http://{fsrv.address}"
    _put_replicated(fsrv, base, "/chaos/dead.bin", payload)
    with failpoint.active("volume.http.read", p=1.0,
                          match=volumes[1].address + ",") as fp:
        for _ in range(10):
            got = requests.get(f"{base}/chaos/dead.bin", timeout=30)
            assert got.status_code == 200
            assert got.content == payload
        assert fp.hits > 0


def test_windowed_readers_survive_flapping_replica_and_degrade(
        chaos_cluster, no_filer_cache):
    """ISSUE 14 chaos: a volume server flapping (100% read failures on
    one replica) under CONCURRENT windowed readers of multi-chunk
    objects. Zero client-visible errors — the chunk-read ladder fails
    over per prefetched chunk exactly as it does sequentially — and
    the readahead window degrades to sequential while the strain
    signal holds (prefetch fan-out must not multiply the error load on
    a struggling cluster)."""
    import threading as _threading

    from seaweedfs_tpu.filer import chunk_pipeline
    from seaweedfs_tpu.qos.pressure import SIGNAL
    from seaweedfs_tpu.utils.stats import CHUNK_PIPELINE_OPS

    master, volumes, fsrv = chaos_cluster
    SIGNAL.reset()
    chunk_pipeline.refresh_config()
    # 20 chunks at the chaos filer's 32KB chunk size: windowed GET
    payload = np.random.default_rng(14).integers(
        0, 256, size=20 * 32 * 1024, dtype=np.uint8).tobytes()
    base = f"http://{fsrv.address}"
    _put_replicated(fsrv, base, "/chaos/windowed.bin", payload)
    collapsed0 = CHUNK_PIPELINE_OPS.value(direction="get",
                                          result="collapsed")
    errors: list[str] = []

    def reader(k: int) -> None:
        for j in range(4):
            try:
                got = requests.get(f"{base}/chaos/windowed.bin",
                                   timeout=60)
                if got.status_code != 200 or got.content != payload:
                    errors.append(f"r{k}.{j}: {got.status_code}")
            except Exception as e:  # noqa: BLE001
                errors.append(f"r{k}.{j}: {type(e).__name__}")

    try:
        with failpoint.active("volume.http.read", p=1.0,
                              match=volumes[0].address + ",") as fp:
            threads = [_threading.Thread(target=reader, args=(k,))
                       for k in range(4)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
            assert fp.hits > 0, "chaos never fired — test is vacuous"
        assert not errors, f"client-visible errors under flap: {errors}"
        # the flap was OBSERVED (per-chunk replica failovers report
        # strain) and the engine responded by collapsing its windows
        assert SIGNAL.status()["strains"] > 0
        assert CHUNK_PIPELINE_OPS.value(
            direction="get", result="collapsed") > collapsed0, \
            "the readahead window never degraded to sequential"
    finally:
        SIGNAL.reset()
        chunk_pipeline.refresh_config()


# -- EC plane: reconstruct around lost shards ------------------------------

def test_ec_read_with_four_lost_shards(chaos_cluster):
    """Lose 4 data shards of an EC volume; reads reconstruct from the
    remaining 10 (acceptance scenario #2), over HTTP and through the
    wdclient EC-fallback ladder."""
    master, volumes, _ = chaos_cluster
    rng = np.random.default_rng(0)
    blobs, fids = {}, []
    for i in range(12):
        data = rng.integers(0, 256, size=int(rng.integers(200, 4000)),
                            dtype=np.uint8).tobytes()
        res = submit(master.address, data, filename=f"c{i}.bin",
                     collection="chaosec")
        assert "fid" in res, res
        fids.append(res["fid"])
        blobs[res["fid"]] = data
    vid = parse_file_id(fids[0]).volume_id
    vsrv = next(v for v in volumes if v.store.has_volume(vid))
    stub = rpc.volume_stub(rpc.grpc_address(vsrv.address))
    stub.VolumeMarkReadonly(vs.VolumeMarkReadonlyRequest(volume_id=vid),
                            timeout=30)
    stub.VolumeEcShardsGenerate(
        vs.VolumeEcShardsGenerateRequest(volume_id=vid,
                                         collection="chaosec"),
        timeout=120)
    stub.VolumeUnmount(vs.VolumeUnmountRequest(volume_id=vid), timeout=30)
    stub.VolumeEcShardsMount(
        vs.VolumeEcShardsMountRequest(volume_id=vid, collection="chaosec",
                                      shard_ids=list(range(14))),
        timeout=30)
    deadline = time.time() + 10
    while time.time() < deadline:
        if vid in master.topo.ec_shard_map and vid not in {
                v for n in master.topo.nodes.values() for v in n.volumes}:
            break
        time.sleep(0.1)

    same_fid = [f for f in fids if parse_file_id(f).volume_id == vid]
    assert same_fid
    lost = "|".join(f"shard={i}," for i in range(4))
    with failpoint.active("ec.shard.read", p=1.0, match=lost) as fp:
        for fid in same_fid:
            got = requests.get(f"http://{vsrv.address}/{fid}", timeout=60)
            assert got.status_code == 200, (fid, got.status_code)
            assert got.content == blobs[fid]
        assert fp.hits > 0, "no shard read was ever injected"
        # wdclient ladder: plain lookup has no replica left -> EC
        # fallback serves the bytes from shard holders
        mc = MasterClient(master.address)
        for fid in same_fid[:3]:
            urls = mc.ec_fallback_urls(fid)
            assert urls, "EC fallback found no shard holders"
            assert requests.get(urls[0], timeout=60).content == blobs[fid]


def test_ec_degraded_flapping_holders_microbatch_and_cache(chaos_cluster):
    """ISSUE 3 scenario: degraded reads under 4-shard loss with FLAPPING
    shard holders and the reconstruct micro-batcher armed — 8 concurrent
    readers, zero client-visible errors — then prove the
    reconstructed-interval cache invalidates on shard remount."""
    import threading

    from seaweedfs_tpu.utils import stats

    master, volumes, _ = chaos_cluster
    rng = np.random.default_rng(5)
    blobs, fids = {}, []
    for i in range(16):
        data = rng.integers(0, 256, size=int(rng.integers(300, 4000)),
                            dtype=np.uint8).tobytes()
        res = submit(master.address, data, filename=f"f{i}.bin",
                     collection="chaosec")
        assert "fid" in res, res
        fids.append(res["fid"])
        blobs[res["fid"]] = data
    by_vid: dict[int, int] = {}
    for f in fids:
        v = parse_file_id(f).volume_id
        by_vid[v] = by_vid.get(v, 0) + 1
    vid = max(by_vid, key=by_vid.get)
    vsrv = next(v for v in volumes if v.store.has_volume(vid))
    stub = rpc.volume_stub(rpc.grpc_address(vsrv.address))
    stub.VolumeMarkReadonly(vs.VolumeMarkReadonlyRequest(volume_id=vid),
                            timeout=30)
    stub.VolumeEcShardsGenerate(
        vs.VolumeEcShardsGenerateRequest(volume_id=vid,
                                         collection="chaosec"),
        timeout=120)
    stub.VolumeUnmount(vs.VolumeUnmountRequest(volume_id=vid), timeout=30)
    stub.VolumeEcShardsMount(
        vs.VolumeEcShardsMountRequest(volume_id=vid, collection="chaosec",
                                      shard_ids=list(range(14))),
        timeout=30)
    same_fid = [f for f in fids if parse_file_id(f).volume_id == vid]
    assert same_fid
    lost = "|".join(f"shard={i}," for i in range(4))

    # phase 1 — flapping holders: lost shards fail ~60% of reads, eight
    # readers hammer concurrently; every read must still return the
    # right bytes while the micro-batcher coalesces reconstructs
    rec0 = stats.ec_dispatch_stats()["reconstruct"]
    with failpoint.active("ec.shard.read", p=0.6, seed=11,
                          match=lost) as fp:
        errs = []
        barrier = threading.Barrier(8)

        def reader():
            try:
                barrier.wait()
                for _ in range(3):
                    for fid in same_fid:
                        got = requests.get(
                            f"http://{vsrv.address}/{fid}", timeout=60)
                        assert got.status_code == 200, (fid,
                                                        got.status_code)
                        assert got.content == blobs[fid], fid
            except BaseException:
                import traceback

                errs.append(traceback.format_exc())

        ths = [threading.Thread(target=reader) for _ in range(8)]
        for t in ths:
            t.start()
        for t in ths:
            t.join()
        assert not errs, errs[0]
        assert fp.hits > 0, "flap never fired — test is vacuous"
    rec1 = stats.ec_dispatch_stats()["reconstruct"]
    assert rec1["slabs"] > rec0["slabs"], \
        "no reconstruct ever rode the dispatch scheduler"

    # phase 2 — deterministic loss fills the interval cache
    def vid_blocks():
        cache = vsrv.ec_recon_cache
        with cache._lock:
            return [k for k in cache._entries if k[0] == vid]

    vsrv.ec_recon_cache.invalidate(vid)
    with failpoint.active("ec.shard.read", p=1.0, match=lost):
        for fid in same_fid:
            got = requests.get(f"http://{vsrv.address}/{fid}", timeout=60)
            assert got.status_code == 200 and got.content == blobs[fid]
    assert vid_blocks(), "cache never populated"

    # phase 3 — remount must provably invalidate the cached intervals
    inv0 = stats.EC_RECON_CACHE_COUNTER.value(result="invalidate")
    stub.VolumeEcShardsUnmount(
        vs.VolumeEcShardsUnmountRequest(volume_id=vid, shard_ids=[0]),
        timeout=30)
    stub.VolumeEcShardsMount(
        vs.VolumeEcShardsMountRequest(volume_id=vid, collection="chaosec",
                                      shard_ids=[0]), timeout=30)
    assert not vid_blocks(), \
        "shard remount left stale reconstructed intervals cached"
    assert stats.EC_RECON_CACHE_COUNTER.value(result="invalidate") > inv0

    # phase 4 — post-remount degraded reads still serve the right bytes
    # (cache repopulates from fresh reconstructs, not stale entries)
    miss0 = stats.EC_RECON_CACHE_COUNTER.value(result="miss")
    with failpoint.active("ec.shard.read", p=1.0, match=lost):
        for fid in same_fid[:4]:
            got = requests.get(f"http://{vsrv.address}/{fid}", timeout=60)
            assert got.status_code == 200 and got.content == blobs[fid]
    assert stats.EC_RECON_CACHE_COUNTER.value(result="miss") > miss0


# -- master plane: leader outage -------------------------------------------

def test_assign_survives_transient_leader_outage(chaos_cluster):
    """The first Assign RPC is injected dead (UNAVAILABLE); the retry
    cycle re-asks after backoff and the assign completes."""
    master, _, _ = chaos_cluster
    # replication 001 reuses the cluster's existing writable volumes —
    # the module cluster is deliberately slot-full by now, and this
    # scenario is about the RPC retry, not volume growth
    with failpoint.active("pb.Assign", p=1.0, count=1) as fp:
        a = assign(master.address, replication="001")
        assert not a.error and a.fid
        assert fp.hits == 1


def test_assign_fails_over_to_new_raft_leader(tmp_path):
    """Kill the real raft leader; assign() walks the master list (dead
    leader first) to whoever leads now (acceptance scenario #3)."""
    ports = [_free_port() for _ in range(3)]
    addrs = [f"localhost:{p}" for p in ports]
    masters = []
    for p in ports:
        ms = MasterServer(ip="localhost", port=p, volume_size_limit_mb=64,
                          peers=list(addrs), raft_dir=str(tmp_path))
        ms.start(vacuum_interval=3600)
        masters.append(ms)
    vsrv = VolumeServer(directories=[str(tmp_path / "v")],
                        master=",".join(addrs), ip="localhost",
                        port=_free_port(), pulse_seconds=1)
    vsrv.start()
    try:
        def wait_leader(pool, timeout=45.0):
            deadline = time.time() + timeout
            while time.time() < deadline:
                leaders = [m for m in pool if m.is_leader()]
                if len(leaders) == 1:
                    return leaders[0]
                time.sleep(0.1)
            return None

        leader = wait_leader(masters)
        assert leader is not None
        deadline = time.time() + 45
        while time.time() < deadline and not leader.topo.nodes:
            time.sleep(0.1)
        assert leader.topo.nodes

        leader.stop()
        survivors = [m for m in masters if m is not leader]
        new_leader = wait_leader(survivors)
        assert new_leader is not None, "no re-election after leader loss"
        deadline = time.time() + 45
        while time.time() < deadline and not new_leader.topo.nodes:
            time.sleep(0.1)
        assert new_leader.topo.nodes, "volume server never re-registered"

        # dead leader deliberately FIRST in the list the client dials
        ordered = [leader.address] + [m.address for m in survivors]
        a = assign(",".join(ordered))
        assert not a.error and a.fid, a.error

        # wdclient re-resolves leadership the same way: starting from
        # the dead leader, RaftListClusterServers via any survivor
        # repoints the client at whoever leads now
        mc = MasterClient(ordered)
        assert mc.resolve_leader() == new_leader.address
        assert mc.current_master == new_leader.address
    finally:
        vsrv.stop()
        for ms in masters:
            ms.stop()
        rpc.reset_channels()


# -- filer metadata plane: flapping store backend --------------------------

def test_filer_write_survives_store_flaps(chaos_cluster):
    """Three consecutive store mutations fail; RetryingStore absorbs
    them and the PUT still lands (then reads back)."""
    _, _, fsrv = chaos_cluster
    base = f"http://{fsrv.address}"
    with failpoint.active("filer.store.mutate", p=1.0, count=3) as fp:
        r = requests.put(f"{base}/chaosfs/retry.txt", data=b"survives",
                         timeout=30)
        assert r.status_code in (200, 201), r.text
        assert fp.hits == 3
    got = requests.get(f"{base}/chaosfs/retry.txt", timeout=30)
    assert got.status_code == 200 and got.content == b"survives"


# -- replication plane: flapping sink --------------------------------------

class _StaticSource:
    def read_entry_content(self, entry: filer_pb2.Entry) -> bytes:
        return bytes(entry.content)


def _create_event(directory: str, name: str, data: bytes):
    ev = filer_pb2.SubscribeMetadataResponse(directory=directory)
    ev.event_notification.new_entry.name = name
    ev.event_notification.new_entry.content = data
    return ev


def test_replication_sink_survives_flaps(tmp_path):
    """The sink bounces the first two applies; the Replicator retries
    instead of dropping the event (acceptance scenario #4)."""
    sink_dir = tmp_path / "mirror"
    repl = Replicator(_StaticSource(), LocalSink(str(sink_dir)),
                      source_prefix="/src", sink_wait_init=0.01)
    with failpoint.active("replication.sink", p=1.0, count=2) as fp:
        assert repl.replicate(_create_event("/src", "a.txt", b"flap"))
        assert fp.hits == 2
    assert (sink_dir / "a.txt").read_bytes() == b"flap"

    # a sink that stays down must surface, not silently drop the event
    with failpoint.active("replication.sink", p=1.0):
        with pytest.raises(IOError):
            repl.replicate(_create_event("/src", "b.txt", b"lost?"))
    assert not (sink_dir / "b.txt").exists()


def test_env_spec_grammar_expresses_shard_targeting():
    """The `@match` part of an SWFS_FAILPOINTS item must round-trip the
    documented shard-targeting form: comma-terminated shard ids with
    `|`-joined alternatives. (Regression: a `;`-terminated ctx
    convention made `@shard=1;` unparseable — the `;` was eaten as the
    item separator, and `|`-alternatives crashed load_env at import.)"""
    failpoint.load_env("ec.shard.read=error(1.0)@shard=1,|shard=4,;"
                       "pb.Assign=error(0.5x2)")
    try:
        assert failpoint.is_armed("ec.shard.read")
        assert failpoint.is_armed("pb.Assign")
        with pytest.raises(failpoint.FailpointError):
            failpoint.fail("ec.shard.read", ctx="v1 shard=4,")
        # shard=10 must NOT be hit by the shard=1 alternative
        failpoint.fail("ec.shard.read", ctx="v1 shard=10,")
        failpoint.fail("ec.shard.read", ctx="v1 shard=2,")
    finally:
        failpoint.clear()


# -- small-file hot path under chaos (ISSUE 2) -----------------------------

def test_cached_chunk_invalidated_on_failover_rewrite(chaos_cluster):
    """Write -> read (chunk now cached at the filer) -> kill one replica
    -> overwrite -> read: the cache must serve the NEW bytes, never the
    invalidated chunk, even while the rewrite itself is failing over
    around the dead replica (ISSUE 2 acceptance: cached chunks are
    invalidated on replica failover re-writes)."""
    master, volumes, fsrv = chaos_cluster
    if fsrv.chunk_cache is None:
        pytest.skip("filer chunk cache disabled in this environment")
    base = f"http://{fsrv.address}"
    old_bytes = b"cache-me-v1 " * 2000
    new_bytes = b"cache-me-v2! " * 2100
    assert requests.put(f"{base}/chaos/cached.bin", data=old_bytes,
                        timeout=30).status_code in (200, 201)
    got = requests.get(f"{base}/chaos/cached.bin", timeout=30)
    assert got.content == old_bytes  # populates the fid-keyed cache
    old_fids = [c.file_id for c in
                fsrv.filer.find_entry("/chaos/cached.bin").chunks]
    assert any(fsrv.chunk_cache.get(f) is not None for f in old_fids), \
        "cache was never populated — the invalidation check is vacuous"
    with failpoint.active("volume.http.read", p=1.0,
                          match=volumes[0].address + ","):
        # the overwrite mints fresh fids and must invalidate the old
        # ones in the cache (write-through + GC invalidation)
        assert requests.put(f"{base}/chaos/cached.bin", data=new_bytes,
                            timeout=30).status_code in (200, 201)
        # the overwrite is only reachable through NEW fids, so the real
        # invalidation evidence is the old fids' cache entries dying
        # (without it, a future fid reuse could resurrect stale bytes)
        for f in old_fids:
            assert fsrv.chunk_cache.get(f) is None, \
                f"old fid {f} still cached after overwrite"
        for _ in range(5):
            got = requests.get(f"{base}/chaos/cached.bin", timeout=30)
            assert got.status_code == 200
            assert got.content == new_bytes, \
                "stale cached chunk served after overwrite"


def test_fid_leases_survive_master_flap_and_upload_failure(chaos_cluster):
    """The filer's fid-lease pool must (a) keep minting fids across a
    transient master outage (assign's PR-1 failover plumbing refills the
    pool) and (b) drop leases + re-lease when an upload to a leased
    volume target fails (the leased volume may be gone after failover)."""
    master, volumes, fsrv = chaos_cluster
    base = f"http://{fsrv.address}"
    fsrv.fid_pool.invalidate(all_keys=True)  # start from a dry pool
    # (a) the refill Assign itself is injected dead once: the pool's
    # batched assign retries through the flap and the PUT still lands
    with failpoint.active("pb.Assign", p=1.0, count=1) as fp:
        r = requests.put(f"{base}/chaoslease/a.txt", data=b"lease-a",
                         timeout=30)
        assert r.status_code in (200, 201), r.text
        assert fp.hits == 1
    assert requests.get(f"{base}/chaoslease/a.txt",
                        timeout=30).content == b"lease-a"
    # the pool is stocked now: the next PUTs must not pay an Assign each
    before = fsrv.fid_pool.remaining()
    assert before > 0, "batched assign left no leased fids in the pool"
    assert requests.put(f"{base}/chaoslease/b.txt", data=b"lease-b",
                        timeout=30).status_code in (200, 201)
    assert fsrv.fid_pool.remaining() < before, \
        "PUT did not drain the lease pool"
    # (b) every upload fails while the failpoint holds: save_chunk must
    # invalidate the pool between attempts (observable as a drained
    # pool) rather than replaying the same dead lease forever
    with failpoint.active("volume.http.write", p=1.0):
        r = requests.put(f"{base}/chaoslease/c.txt", data=b"lease-c",
                         timeout=30)
        assert r.status_code == 500  # both lease targets injected dead
    assert fsrv.fid_pool.remaining() == 0, \
        "failed upload left stale leases in the pool"
    # with the fault gone the pool re-leases from scratch and recovers
    assert requests.put(f"{base}/chaoslease/c.txt", data=b"lease-c",
                        timeout=30).status_code in (200, 201)
    assert requests.get(f"{base}/chaoslease/c.txt",
                        timeout=30).content == b"lease-c"


def test_group_commit_acked_writes_are_os_visible(chaos_cluster):
    """Concurrent PUTs through the python volume plane (group commit
    batches their flushes); after every ack the needle bytes must be
    visible through an INDEPENDENT file descriptor — i.e. they reached
    the OS, not just a user-space buffer (ISSUE 2 acceptance: group
    commit never acks a write whose bytes didn't reach the OS)."""
    import concurrent.futures as cf
    import glob as _glob
    import os as _os

    master, volumes, fsrv = chaos_cluster
    rng = np.random.default_rng(42)
    # incompressible payloads: the upload path would gzip repetitive
    # bytes, and this test byte-searches the raw .dat files
    payloads = {f"/chaosgc/f{i:03d}.bin":
                rng.integers(0, 256, size=500 + 37 * i,
                             dtype=np.uint8).tobytes() for i in range(24)}
    base = f"http://{fsrv.address}"

    def put(item):
        path, data = item
        r = requests.put(base + path, data=data, timeout=30)
        return path, r.status_code

    with cf.ThreadPoolExecutor(max_workers=8) as ex:
        for path, status in ex.map(put, payloads.items()):
            assert status in (200, 201), path
    # group commit engaged (the counter is process-global, so only
    # assert it moved — batching ratios are timing-dependent)
    from seaweedfs_tpu.utils.stats import group_commit_stats
    st = group_commit_stats()
    assert st["writes"] > 0 and st["flushes"] > 0
    # OS-visibility: read every .dat through FRESH descriptors, never
    # through the volume objects (whose read path may flush buffers on
    # demand) — after the ack, the bytes must already be in the OS
    raw = b""
    for vsrv in volumes:
        for loc in vsrv.store.locations:
            for dat in _glob.glob(_os.path.join(loc.directory, "*.dat")):
                with open(dat, "rb") as f:
                    raw += f.read()
    for path, data in payloads.items():
        assert data in raw, \
            f"acked write {path} not visible through the OS"


# -- integrity plane (ISSUE 4): failpoint rot -> scrub detect -> self-heal -


def _assign_put_both(master, volumes, payload, attempts=8):
    """Direct-volume PUT with replication 001, proven on both replicas
    -> fid."""
    for _ in range(attempts):
        a = assign(master.address, replication="001")
        if a.error:
            time.sleep(0.3)
            continue
        r = requests.put(f"http://{a.url}/{a.fid}", data=payload,
                         timeout=30)
        if r.status_code not in (200, 201):
            time.sleep(0.3)
            continue
        vid = parse_file_id(a.fid).volume_id
        deadline = time.time() + 8
        while time.time() < deadline:
            if all(v.store.has_volume(vid) and
                   requests.get(f"http://{v.address}/{a.fid}",
                                timeout=10).status_code == 200
                   for v in volumes):
                return a.fid
            time.sleep(0.2)
    raise AssertionError("payload never landed on both replicas")


def test_scrub_detects_and_repairs_corrupt_replica_needle(
        chaos_cluster, no_filer_cache):
    """Acceptance: a failpoint-corrupted replica needle is detected by
    the BACKGROUND scrubber (not a client read), repaired by
    re-replication from the healthy copy, re-verified clean — with zero
    client-visible errors throughout and the SeaweedFS_scrub_* counters
    + scrub status reflecting the find -> repair -> clean lifecycle."""
    from seaweedfs_tpu.pb import scrub_pb2
    from seaweedfs_tpu.utils.stats import SCRUB_FINDINGS

    master, volumes, fsrv = chaos_cluster
    base = f"http://{fsrv.address}"
    v1 = b"scrub-needle v1 " * 800
    v2 = b"scrub-needle V2! " * 800
    _put_replicated(fsrv, base, "/scrub/rot.bin", v1)
    bad_dir = volumes[1].store.locations[0].directory
    # the overwrite's bytes rot ON DISK on volumes[1] only — the client
    # PUT itself succeeds everywhere (bit rot, not a failed write)
    with failpoint.active("volume.dat.write.corrupt", mode="corrupt",
                          p=1.0, match=bad_dir + ",") as fp:
        r = requests.put(base + "/scrub/rot.bin", data=v2, timeout=30)
        assert r.status_code in (200, 201), r.text
        assert fp.hits > 0, "corruption never landed — test is vacuous"
    fids = [c.file_id for c in fsrv.filer.find_entry("/scrub/rot.bin").chunks]
    vids = sorted({parse_file_id(f).volume_id for f in fids})

    found0 = SCRUB_FINDINGS.value(kind="needle_crc", state="found")
    rep0 = SCRUB_FINDINGS.value(kind="needle_crc", state="repaired")

    # concurrent readers while the scrubber detects + repairs: the filer
    # ladder fails over around the rotten replica — zero visible errors
    import threading as _threading

    errs, stop_readers = [], _threading.Event()

    def reader():
        while not stop_readers.is_set():
            try:
                got = requests.get(base + "/scrub/rot.bin", timeout=30)
                assert got.status_code == 200 and got.content == v2
            except BaseException:
                import traceback

                errs.append(traceback.format_exc())
                return

    ths = [_threading.Thread(target=reader) for _ in range(4)]
    for t in ths:
        t.start()
    try:
        reports = [volumes[1].scrubber.run_once(vid=vid) for vid in vids]
    finally:
        stop_readers.set()
        for t in ths:
            t.join()
    assert not errs, errs[0]
    findings = [f for r in reports for f in r.findings
                if f.kind == "needle_crc"]
    assert findings, "scrubber never detected the injected rot"
    assert all(f.state == "repaired" for f in findings), findings
    assert SCRUB_FINDINGS.value(kind="needle_crc", state="found") > found0
    assert SCRUB_FINDINGS.value(kind="needle_crc", state="repaired") > rep0

    # repaired replica serves the right bytes ALONE (other replica dead)
    with failpoint.active("volume.http.read", p=1.0,
                          match=volumes[0].address + ","):
        got = requests.get(base + "/scrub/rot.bin", timeout=30)
        assert got.status_code == 200 and got.content == v2
    # lifecycle visible through the status RPC
    stub = rpc.volume_stub(rpc.grpc_address(volumes[1].address))
    st = stub.ScrubStatus(scrub_pb2.ScrubStatusRequest(), timeout=30)
    assert any(f.kind == "needle_crc" and f.state == "repaired"
               for f in st.findings)
    # a fresh full sweep of the repaired volumes is clean — converged
    for vid in vids:
        r = volumes[1].scrubber.run_once(vid=vid, full=True)
        assert not [f for f in r.findings if f.kind == "needle_crc"], \
            r.findings


def test_scrub_detects_and_repairs_corrupt_ec_shard(chaos_cluster):
    """Acceptance: a failpoint-corrupted EC DATA shard under concurrent
    readers — reads self-heal by reconstructing around the rotten shard
    (zero client-visible errors), the scrubber's syndrome sweep pins the
    culprit, the rebuild repair converges, and a fresh sweep is clean."""
    from seaweedfs_tpu.utils.stats import SCRUB_FINDINGS, SCRUB_REPAIRS

    master, volumes, _ = chaos_cluster
    rng = np.random.default_rng(21)
    blobs, fids = {}, []
    for i in range(14):
        data = rng.integers(0, 256, size=int(rng.integers(300, 3000)),
                            dtype=np.uint8).tobytes()
        res = submit(master.address, data, filename=f"s{i}.bin",
                     collection="chaosec")  # reuse the module cluster's
        # existing collection: its writable volumes survive earlier tests,
        # while growing a fresh collection would need slots the now-full
        # cluster no longer has
        assert "fid" in res, res
        fids.append(res["fid"])
        blobs[res["fid"]] = data
    by_vid: dict[int, int] = {}
    for f in fids:
        vv = parse_file_id(f).volume_id
        by_vid[vv] = by_vid.get(vv, 0) + 1
    vid = max(by_vid, key=by_vid.get)
    vsrv = next(v for v in volumes if v.store.has_volume(vid))
    stub = rpc.volume_stub(rpc.grpc_address(vsrv.address))
    stub.VolumeMarkReadonly(vs.VolumeMarkReadonlyRequest(volume_id=vid),
                            timeout=30)
    # shard 3 (a data shard) rots AS IT IS WRITTEN during ec.encode
    with failpoint.active("ec.shard.write.corrupt", mode="corrupt",
                          p=1.0, match="shard=3,") as fp:
        stub.VolumeEcShardsGenerate(
            vs.VolumeEcShardsGenerateRequest(volume_id=vid,
                                             collection="chaosec"),
            timeout=120)
        assert fp.hits > 0, "shard corruption never fired"
    stub.VolumeUnmount(vs.VolumeUnmountRequest(volume_id=vid), timeout=30)
    stub.VolumeEcShardsMount(
        vs.VolumeEcShardsMountRequest(volume_id=vid, collection="chaosec",
                                      shard_ids=list(range(14))),
        timeout=30)
    same_fid = [f for f in fids if parse_file_id(f).volume_id == vid]
    assert same_fid
    found0 = SCRUB_FINDINGS.value(kind="ec_parity", state="found")
    repaired0 = SCRUB_REPAIRS.value(method="ec_rebuild", outcome="ok")

    # concurrent readers against the rotten shard: every read serves the
    # right bytes (CRC failure degrades to reconstruct-around-the-shard)
    import threading as _threading

    errs = []
    barrier = _threading.Barrier(6)

    def reader():
        try:
            barrier.wait()
            for _ in range(2):
                for fid in same_fid:
                    got = requests.get(f"http://{vsrv.address}/{fid}",
                                       timeout=60)
                    assert got.status_code == 200, (fid, got.status_code)
                    assert got.content == blobs[fid], fid
        except BaseException:
            import traceback

            errs.append(traceback.format_exc())

    ths = [_threading.Thread(target=reader) for _ in range(6)]
    for t in ths:
        t.start()
    for t in ths:
        t.join()
    assert not errs, errs[0]

    # the scrubber pins the culprit and rebuilds it. The readers'
    # report_suspect() may have ALREADY woken the background daemon and
    # repaired before this explicit pass — either path must land the
    # same find -> repair lifecycle in the registry and counters.
    vsrv.scrubber.run_once(vid=vid, full=True)
    culprits = [(f.shard_id, f.state)
                for f in vsrv.scrubber.snapshot_findings()
                if f.kind == "ec_parity" and f.volume_id == vid]
    assert (3, "repaired") in culprits, culprits
    assert SCRUB_FINDINGS.value(kind="ec_parity", state="found") > found0
    assert SCRUB_REPAIRS.value(method="ec_rebuild",
                               outcome="ok") > repaired0

    # converged: clean syndrome, clean reads, no failpoints armed
    r2 = vsrv.scrubber.run_once(vid=vid, full=True)
    assert not [f for f in r2.findings if f.kind == "ec_parity"], r2.findings
    for fid in same_fid:
        got = requests.get(f"http://{vsrv.address}/{fid}", timeout=60)
        assert got.status_code == 200 and got.content == blobs[fid]


def test_anti_entropy_heals_replica_diverged_under_failpoint(chaos_cluster):
    """Acceptance: a replica re-written while the OTHER replica's write
    plane was failpoint-dead diverges; digest anti-entropy detects it
    (rolling CRCs differ), ships only the diverging needle, and the
    newest write wins on both replicas — readers see zero errors
    throughout."""
    from seaweedfs_tpu.pb import scrub_pb2
    from seaweedfs_tpu.utils.stats import SCRUB_REPAIRS

    master, volumes, _ = chaos_cluster
    v1 = b"anti-entropy v1 " * 500
    v2 = b"anti-entropy V2! " * 500
    fid = _assign_put_both(master, volumes, v1)
    vid = parse_file_id(fid).volume_id
    primary = next(v for v in volumes if v.store.has_volume(vid))
    other = next(v for v in volumes if v is not primary)
    # the overwrite lands on the primary; replication to the other
    # replica is injected dead -> divergence (the PUT surfaces the
    # replication failure, as it must — data planes don't lie)
    with failpoint.active("volume.http.write", p=1.0,
                          match=other.address + ",") as fp:
        r = requests.put(f"http://{primary.address}/{fid}", data=v2,
                         timeout=30)
        assert r.status_code == 500  # replication failure is surfaced
        assert fp.hits > 0
    # divergence is real: primary serves v2, the other replica v1
    assert requests.get(f"http://{primary.address}/{fid}",
                        timeout=30).content == v2
    assert requests.get(f"http://{other.address}/{fid}",
                        timeout=30).content == v1

    # readers during the heal: zero errors (stale-or-fresh, never broken)
    import threading as _threading

    errs, stop_readers = [], _threading.Event()

    def reader(addr):
        while not stop_readers.is_set():
            try:
                got = requests.get(f"http://{addr}/{fid}", timeout=30)
                assert got.status_code == 200
                assert got.content in (v1, v2)
            except BaseException:
                import traceback

                errs.append(traceback.format_exc())
                return

    ths = [_threading.Thread(target=reader, args=(v.address,))
           for v in volumes for _ in range(2)]
    for t in ths:
        t.start()
    try:
        report = primary.scrubber.run_once(vid=vid)
    finally:
        stop_readers.set()
        for t in ths:
            t.join()
    assert not errs, errs[0]
    div = [f for f in report.findings if f.kind == "replica_divergence"]
    assert div and all(f.state == "repaired" for f in div), report.findings
    assert SCRUB_REPAIRS.value(method="anti_entropy", outcome="ok") > 0

    # converged on the newest write, on BOTH replicas
    for v in volumes:
        got = requests.get(f"http://{v.address}/{fid}", timeout=30)
        assert got.status_code == 200 and got.content == v2
    digests = set()
    for v in volumes:
        stub = rpc.volume_stub(rpc.grpc_address(v.address))
        d = stub.VolumeDigest(scrub_pb2.VolumeDigestRequest(volume_id=vid),
                              timeout=30)
        digests.add((d.rolling_crc, d.needle_count, d.tombstone_count))
    assert len(digests) == 1, f"replicas still diverge: {digests}"


# -- subprocess stacks: SWFS_FAILPOINTS env bootstrap ----------------------

def test_env_failpoint_drives_subprocess_server(tmp_path):
    """A spawned `weed server` arms failpoints from SWFS_FAILPOINTS: the
    first volume read 500s, the x1 count bound then expires and the
    retry succeeds — proving the chaos plumbing reaches real
    subprocess stacks, not just in-process servers."""
    mport, vport = _free_port(), _free_port()
    env = dict(os.environ, SEAWEEDFS_TPU_CODER="native",
               SWFS_FAILPOINTS="volume.http.read=error(1.0x1)")
    env.pop("SEAWEEDFS_TPU_NATIVE", None)
    log_path = tmp_path / "server.log"
    with open(log_path, "w") as log:
        proc = subprocess.Popen(
            [sys.executable, "-m", "seaweedfs_tpu", "server",
             "-dir", str(tmp_path), "-master.port", str(mport),
             "-volume.port", str(vport),
             "-volume.nativeDataPlane", "off"],
            env=env, stdout=log, stderr=subprocess.STDOUT)
    try:
        deadline = time.time() + 120
        res = None
        while time.time() < deadline:
            if proc.poll() is not None:
                pytest.fail("server died at startup:\n"
                            + log_path.read_text()[-2000:])
            try:
                res = submit(f"localhost:{mport}", b"env-chaos",
                             filename="e.bin")
                if "fid" in res:
                    break
            except Exception:
                pass
            time.sleep(1.0)
        assert res and "fid" in res, res
        url = f"http://{res['url']}/{res['fid']}"
        first = requests.get(url, timeout=10)
        assert first.status_code == 500, "env failpoint never armed"
        second = requests.get(url, timeout=10)
        assert second.status_code == 200
        assert second.content == b"env-chaos"
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()


# -- QoS plane (ISSUE 8): qos.grant outage — open for foreground, ----------
#    closed for background

def test_qos_grant_outage_foreground_open_background_closed(
        chaos_cluster, monkeypatch):
    """The `qos.grant` failpoint severs the volume servers' lease plane
    (master unreachable mid-lease). Invariants the QoS plane promises:

      * foreground I/O FAILS OPEN — filer writes and reads never touch
        the grant plane, so a dead QoS master cannot deadlock a client
        (zero client-visible errors while the outage lasts);
      * background FAILS CLOSED — a scrub token acquire raises
        QosUnavailable, the real scrub pass pauses WITHOUT surfacing an
        error anywhere, and an archival `VolumeEcShardsGenerate` aborts
        RESOURCE_EXHAUSTED before touching bytes;
      * recovery — once the plane heals, the same background calls are
        served again.
    """
    import grpc

    from seaweedfs_tpu.qos import QosUnavailable

    master, volumes, fsrv = chaos_cluster
    # activate the cluster budget: background must now hold a lease
    monkeypatch.setenv("SWFS_QOS_BG_MBPS", "4")
    base = f"http://{fsrv.address}"

    # the preceding subprocess test's rpc.reset_channels() severs this
    # cluster's heartbeat streams; the master defer-unregisters both
    # nodes for ~1s until the next pulse — assign would see an empty
    # topology ("no free volume slot"), so wait for re-registration
    deadline = time.time() + 10
    while time.time() < deadline and len(master.topo.nodes) < 2:
        time.sleep(0.05)
    assert len(master.topo.nodes) == 2, master.topo.nodes

    # stage a volume with real needles on a known server (the scrub
    # sweep and the archival encode both need bytes to pace)
    rng = np.random.default_rng(8)
    res = submit(master.address, rng.integers(
        0, 256, size=5000, dtype=np.uint8).tobytes(),
        filename="q.bin", collection="qoschaos")
    assert "fid" in res, res
    vid = parse_file_id(res["fid"]).volume_id
    vsrv = next(v for v in volumes if v.store.has_volume(vid))
    stub = rpc.volume_stub(rpc.grpc_address(vsrv.address))

    with failpoint.active("qos.grant", mode="error", p=1.0) as fp:
        # background fails CLOSED: the direct token path raises...
        with pytest.raises(QosUnavailable):
            vsrv.qos_governor.acquire("scrub", 1 << 20, max_wait_s=2.0)
        # ...the real sweep turns that into a paused pass, not an error
        report = vsrv.scrubber.run_once(vid=vid, full=True)
        assert not report.findings  # paused, nothing half-scanned

        # archival aborts before touching data
        stub.VolumeMarkReadonly(
            vs.VolumeMarkReadonlyRequest(volume_id=vid), timeout=30)
        with pytest.raises(grpc.RpcError) as ei:
            stub.VolumeEcShardsGenerate(
                vs.VolumeEcShardsGenerateRequest(
                    volume_id=vid, collection="qoschaos"), timeout=120)
        assert ei.value.code() == grpc.StatusCode.RESOURCE_EXHAUSTED

        # meanwhile foreground I/O sails through the same outage:
        # zero client-visible errors on writes OR reads
        for i in range(15):
            w = requests.put(f"{base}/qoschaos/fg{i}.bin",
                             data=b"fail-open " * 50, timeout=30)
            assert w.status_code in (200, 201), w.text
            g = requests.get(f"{base}/qoschaos/fg{i}.bin", timeout=30)
            assert g.status_code == 200
            assert g.content == b"fail-open " * 50
        assert fp.hits > 0, "qos.grant chaos never fired — vacuous"

    # plane healed: the in-process master serves the lease again and
    # the SAME background calls are admitted
    assert vsrv.qos_governor.acquire("scrub", 1024, max_wait_s=10.0) \
        >= 0.0
    stub.VolumeEcShardsGenerate(
        vs.VolumeEcShardsGenerateRequest(volume_id=vid,
                                         collection="qoschaos"),
        timeout=120)


# -- code-geometry plane (ISSUE 11): LRC degraded reads + scrub heal --------

def test_lrc_degraded_reads_and_scrub_heals_group_and_global_loss(
        chaos_cluster):
    """Acceptance: an lrc_10_2_2 volume (a) serves correct bytes under a
    lost LOCAL-GROUP shard via the minimal-read plan (5 survivors, not
    10 — pinned by the per-geometry repair counters), and (b) the scrub
    repair ladder heals BOTH a local-group shard and a GLOBAL parity
    shard rot to convergence, with concurrent readers seeing zero
    errors throughout."""
    import threading as _threading

    import grpc

    from seaweedfs_tpu.pb import ec_geometry_pb2 as eg
    from seaweedfs_tpu.utils.stats import (
        EC_REPAIR_BYTES,
        EC_REPAIR_PLANS,
        SCRUB_REPAIRS,
    )

    master, volumes, _ = chaos_cluster
    rng = np.random.default_rng(61)
    blobs, fids = {}, []
    for i in range(14):
        data = rng.integers(0, 256, size=int(rng.integers(300, 3000)),
                            dtype=np.uint8).tobytes()
        res = submit(master.address, data, filename=f"lrc{i}.bin",
                     collection="chaosec")
        assert "fid" in res, res
        fids.append(res["fid"])
        blobs[res["fid"]] = data
    by_vid: dict[int, int] = {}
    for f in fids:
        vv = parse_file_id(f).volume_id
        by_vid[vv] = by_vid.get(vv, 0) + 1
    vid = max(by_vid, key=by_vid.get)
    vsrv = next(v for v in volumes if v.store.has_volume(vid))
    stub = rpc.volume_stub(rpc.grpc_address(vsrv.address))
    stub.VolumeMarkReadonly(vs.VolumeMarkReadonlyRequest(volume_id=vid),
                            timeout=30)
    # an unknown geometry name is refused with the registered list
    with pytest.raises(grpc.RpcError) as ei:
        stub.VolumeEcShardsGenerate(
            eg.EcGenerateRequest(volume_id=vid, collection="chaosec",
                                 geometry="fountain_42"),
            timeout=30)
    assert ei.value.code() == grpc.StatusCode.INVALID_ARGUMENT
    assert "lrc_10_2_2" in ei.value.details()
    # geometry-aware generate: the registry name rides the RPC
    stub.VolumeEcShardsGenerate(
        eg.EcGenerateRequest(volume_id=vid, collection="chaosec",
                             geometry="lrc_10_2_2"),
        timeout=120)
    stub.VolumeUnmount(vs.VolumeUnmountRequest(volume_id=vid), timeout=30)
    stub.VolumeEcShardsMount(
        vs.VolumeEcShardsMountRequest(volume_id=vid, collection="chaosec",
                                      shard_ids=list(range(14))),
        timeout=30)
    ev = vsrv.store.find_ec_volume(vid)
    assert ev is not None and ev.geo.code_name == "lrc_10_2_2"
    assert ev.coder.geometry_id == "lrc_10_2_2"
    same_fid = [f for f in fids if parse_file_id(f).volume_id == vid]
    assert same_fid

    # phase 1 — degraded reads with shard 0 (group A) failpoint-lost:
    # every read serves the right bytes through the 5-survivor plan
    plans0 = EC_REPAIR_PLANS.value(geometry="lrc_10_2_2",
                                   kind="degraded_read")
    bytes0 = EC_REPAIR_BYTES.value(geometry="lrc_10_2_2",
                                   kind="degraded_read")
    with failpoint.active("ec.shard.read", p=1.0, match="shard=0,") as fp:
        for fid in same_fid:
            got = requests.get(f"http://{vsrv.address}/{fid}", timeout=60)
            assert got.status_code == 200, (fid, got.status_code)
            assert got.content == blobs[fid]
        assert fp.hits > 0, "no shard read was ever injected"
    plans = EC_REPAIR_PLANS.value(geometry="lrc_10_2_2",
                                  kind="degraded_read") - plans0
    moved = EC_REPAIR_BYTES.value(geometry="lrc_10_2_2",
                                  kind="degraded_read") - bytes0
    assert plans > 0, "no lrc repair plan executed"
    assert moved > 0
    # the headline: every group-shard plan read exactly 5 survivor rows
    # of its interval size (RS reads 10) — so the moved total is 5x the
    # reconstructed extent, never 10x
    assert moved % 5 == 0, moved

    # phase 2 — scrub heals a LOCAL-GROUP shard rot (shard 0) and then
    # a GLOBAL parity rot (shard 13), each under concurrent readers
    repaired0 = SCRUB_REPAIRS.value(method="ec_rebuild", outcome="ok")
    for bad in (0, 13):
        path = ev.geo.shard_file_name(ev.base, bad)
        with open(path, "r+b") as fh:
            fh.seek(29)
            b = fh.read(1)
            fh.seek(-1, 1)
            fh.write(bytes([b[0] ^ 0x77]))
        # bounded concurrent readers against the rotten shard first
        # (unbounded readers would hold the scrubber in FG-QPS backoff
        # for minutes): every read serves the right bytes
        errs = []
        barrier = _threading.Barrier(3)

        def reader():
            try:
                barrier.wait()
                for _ in range(2):
                    for fid in same_fid[:4]:
                        got = requests.get(
                            f"http://{vsrv.address}/{fid}", timeout=60)
                        assert got.status_code == 200
                        assert got.content == blobs[fid]
            except BaseException:
                import traceback

                errs.append(traceback.format_exc())

        ths = [_threading.Thread(target=reader) for _ in range(3)]
        for t in ths:
            t.start()
        for t in ths:
            t.join()
        assert not errs, errs[0]
        vsrv.scrubber.run_once(vid=vid, full=True)
        culprits = [(f.shard_id, f.state)
                    for f in vsrv.scrubber.snapshot_findings()
                    if f.kind == "ec_parity" and f.volume_id == vid]
        assert (bad, "repaired") in culprits, (bad, culprits)
    assert SCRUB_REPAIRS.value(method="ec_rebuild",
                               outcome="ok") >= repaired0 + 2

    # converged: clean sweep, correct bytes everywhere
    r2 = vsrv.scrubber.run_once(vid=vid, full=True)
    assert not [f for f in r2.findings if f.kind == "ec_parity"], \
        r2.findings
    for fid in same_fid:
        got = requests.get(f"http://{vsrv.address}/{fid}", timeout=60)
        assert got.status_code == 200 and got.content == blobs[fid]


# -- cluster integrity fabric (ISSUE 13): cross-server syndrome verify ------
#    + per-needle causality

def test_read_corrupt_failpoint_injects_on_the_wire(chaos_cluster):
    """`volume.http.read.corrupt` flips a served needle's first data
    byte AFTER storage verification — wire/NIC rot the storage CRCs
    cannot see. Pin that the hook actually fires (and stops when
    disarmed) so the chaos registry never carries a dead site."""
    master, volumes, fsrv = chaos_cluster
    payload = b"wire-rot " * 200
    fid = _assign_put_both(master, volumes, payload)
    target = next(v for v in volumes
                  if v.store.has_volume(parse_file_id(fid).volume_id))
    with failpoint.active("volume.http.read.corrupt", mode="corrupt",
                          p=1.0, match=target.address + ",") as fp:
        got = requests.get(f"http://{target.address}/{fid}", timeout=30)
        assert got.status_code == 200
        assert got.content != payload, "corruption never injected"
        assert got.content[1:] == payload[1:]  # exactly one byte flipped
        assert fp.hits > 0
    got = requests.get(f"http://{target.address}/{fid}", timeout=30)
    assert got.content == payload  # disarmed: clean bytes again


def test_cross_server_scrub_flap_resume_and_remote_rot_heal(
        chaos_cluster, tmp_path):
    """ISSUE-13 acceptance: an EC volume whose shards are split THREE
    ways (no holder has k data shards) is cross-server
    syndrome-verified, not skipped. One peer flaps mid-gather — the
    resume re-fetches ONLY the missing ranges (exact byte accounting).
    Then rot planted on a REMOTE shard is detected, pinned, rebuilt
    from cross-server survivors and re-verified to convergence, with
    concurrent readers seeing zero errors throughout."""
    import threading as _threading

    from seaweedfs_tpu.pb import ec_geometry_pb2 as eg
    from seaweedfs_tpu.server.volume import VolumeServer
    from seaweedfs_tpu.storage.file_id import format_needle_id_cookie
    from seaweedfs_tpu.storage.needle import Needle
    from seaweedfs_tpu.utils.stats import (
        SCRUB_GATHER_BYTES,
        SCRUB_GATHER_RESUMES,
        SCRUB_REPAIRS,
    )

    master, volumes, _ = chaos_cluster
    a, b = volumes
    c = VolumeServer(directories=[str(tmp_path / "volC")],
                     master=master.address, ip="localhost",
                     port=_free_port(), pulse_seconds=1,
                     ec_geometry=TEST_GEO)
    c.start()
    try:
        deadline = time.time() + 10
        while time.time() < deadline and len(master.topo.nodes) < 3:
            time.sleep(0.05)
        assert len(master.topo.nodes) == 3

        # --- stage: volume on A, EC'd, shards split A:0-4 B:5-9 C:10-13
        vid = 7801
        v = a.store.add_volume(vid)
        rng = np.random.default_rng(42)
        blobs = {}
        for i in range(1, 31):
            # enough bytes that each shard spans several 4KB gather
            # slabs — the mid-stream flap needs a window boundary to
            # kill and a tail for the resume to re-fetch
            data = rng.integers(0, 256, size=int(rng.integers(2000, 6000)),
                                dtype=np.uint8).tobytes()
            v.write_needle(Needle.create(i, 0xABC, data))
            blobs[i] = data
        a.trigger_heartbeat()
        stub_a = rpc.volume_stub(rpc.grpc_address(a.address))
        stub_b = rpc.volume_stub(rpc.grpc_address(b.address))
        stub_c = rpc.volume_stub(rpc.grpc_address(c.address))
        stub_a.VolumeMarkReadonly(
            vs.VolumeMarkReadonlyRequest(volume_id=vid), timeout=30)
        stub_a.VolumeEcShardsGenerate(
            eg.EcGenerateRequest(volume_id=vid), timeout=120)
        for stub, sids in ((stub_b, list(range(5, 10))),
                          (stub_c, list(range(10, 14)))):
            stub.VolumeEcShardsCopy(
                vs.VolumeEcShardsCopyRequest(
                    volume_id=vid, shard_ids=sids, copy_ecx_file=True,
                    copy_vif_file=True, source_data_node=a.address),
                timeout=120)
        stub_a.VolumeUnmount(vs.VolumeUnmountRequest(volume_id=vid),
                             timeout=30)
        stub_a.VolumeEcShardsDelete(
            vs.VolumeEcShardsDeleteRequest(volume_id=vid,
                                           shard_ids=list(range(5, 14))),
            timeout=30)
        stub_a.VolumeEcShardsMount(
            vs.VolumeEcShardsMountRequest(volume_id=vid,
                                          shard_ids=list(range(5))),
            timeout=30)
        stub_b.VolumeEcShardsMount(
            vs.VolumeEcShardsMountRequest(volume_id=vid,
                                          shard_ids=list(range(5, 10))),
            timeout=30)
        stub_c.VolumeEcShardsMount(
            vs.VolumeEcShardsMountRequest(volume_id=vid,
                                          shard_ids=list(range(10, 14))),
            timeout=30)
        deadline = time.time() + 15
        while time.time() < deadline and \
                len(master.topo.lookup_ec_shards(vid) or {}) != 14:
            time.sleep(0.2)
        assert len(master.topo.lookup_ec_shards(vid) or {}) == 14
        ev_c = c.store.find_ec_volume(vid)
        assert sorted(ev_c.shard_files) == [10, 11, 12, 13]
        shard_size = ev_c.shard_size
        c.scrubber.ec_slab = 4096  # several gather windows per shard

        def read_all(server):
            for i, data in blobs.items():
                fid = f"{vid},{format_needle_id_cookie(i, 0xABC)}"
                got = requests.get(f"http://{server.address}/{fid}",
                                   timeout=60)
                assert got.status_code == 200, (fid, got.status_code)
                assert got.content == data, fid

        # --- phase 1: clean cross-server verify with a mid-gather flap.
        # C's 4 parity targets plan k=10 reads -> shards 0..9 gathered.
        flap_off = 4096  # one gather-slab boundary into each stream
        assert shard_size > flap_off + 4096, shard_size
        live0 = SCRUB_GATHER_BYTES.value(phase="live")
        res0 = SCRUB_GATHER_BYTES.value(phase="resume")
        n_res0 = SCRUB_GATHER_RESUMES.value()
        with failpoint.active("scrub.gather.range", p=1.0, count=1,
                              match=f"off={flap_off},") as fp:
            report = c.scrubber.run_once(vid=vid, full=True)
            assert fp.hits == 1, "gather flap never fired — vacuous"
        assert [f.detail for f in report.findings] == []
        live_d = SCRUB_GATHER_BYTES.value(phase="live") - live0
        res_d = SCRUB_GATHER_BYTES.value(phase="resume") - res0
        assert SCRUB_GATHER_RESUMES.value() - n_res0 == 1
        # resume re-fetched ONLY the missing tail of the flapped stream
        assert res_d == shard_size - flap_off, (res_d, shard_size)
        # and nothing was moved twice: live + resume == exactly the
        # 10-shard plan's worth of ranges
        assert live_d + res_d == 10 * shard_size, (live_d, res_d)

        # --- phase 2: rot on a shard REMOTE from the scrubbing holder
        ev_a = a.store.find_ec_volume(vid)
        rot_path = ev_a.geo.shard_file_name(ev_a.base, 3)
        with open(rot_path, "r+b") as fh:
            fh.seek(57)
            orig = fh.read(1)
            fh.seek(-1, 1)
            fh.write(bytes([orig[0] ^ 0x5A]))

        errs = []
        stop_readers = _threading.Event()

        def reader():
            while not stop_readers.is_set():
                try:
                    read_all(c)
                except BaseException:
                    import traceback

                    errs.append(traceback.format_exc())
                    return

        ths = [_threading.Thread(target=reader) for _ in range(2)]
        for t in ths:
            t.start()
        try:
            rep0 = SCRUB_REPAIRS.value(method="ec_rebuild", outcome="ok")
            report = c.scrubber.run_once(vid=vid, full=True)
        finally:
            stop_readers.set()
            for t in ths:
                t.join()
        assert not errs, errs[0]
        culprits = [(f.shard_id, f.state) for f in report.findings
                    if f.kind == "ec_parity"]
        assert (3, "repaired") in culprits, culprits
        assert SCRUB_REPAIRS.value(method="ec_rebuild",
                                   outcome="ok") > rep0
        # the verified rebuild MIGRATED to the scrubbing holder and the
        # rotten remote copy is gone
        assert 3 in c.store.find_ec_volume(vid).shard_files
        assert not os.path.exists(rot_path)

        # --- converged: a fresh cross-server sweep is clean, reads are
        # correct from every holder
        r2 = c.scrubber.run_once(vid=vid, full=True)
        assert not [f for f in r2.findings if f.kind == "ec_parity"], \
            r2.findings
        read_all(c)
        read_all(b)
    finally:
        c.stop()


def test_same_timestamp_conflict_autoresolves_via_epoch_tags(
        chaos_cluster):
    """ISSUE-13 acceptance (tentpole b): a same-`append_at_ns` dual
    write — the one divergence class PR-4 surfaced to operators —
    converges with NO failed finding: the replica-epoch total order
    picks the same winner on both sides, readers see zero errors, and
    the digests land identical."""
    import threading as _threading

    from seaweedfs_tpu.pb import scrub_pb2
    from seaweedfs_tpu.storage import types as _types

    master, volumes, _ = chaos_cluster
    base_payload = b"conflict base " * 300
    fid = _assign_put_both(master, volumes, base_payload)
    f = parse_file_id(fid)
    vid = f.volume_id
    primary = next(v for v in volumes if v.store.has_volume(vid))
    other = next(v for v in volumes if v is not primary)

    # dual write: each replica accepts a DIFFERENT body with no fan-out
    v2a = b"conflict wins A " * 300
    v2b = b"conflict wins B " * 300
    for srv, body in ((primary, v2a), (other, v2b)):
        r = requests.put(f"http://{srv.address}/{fid}?type=replicate",
                         data=body, timeout=30)
        assert r.status_code in (200, 201), r.text

    # force the unorderable case: patch both records' append_at_ns to
    # the SAME value on disk (the v3 tail: crc(4) then ns(8))
    same_ns = 7_000_000_000_000_000_000
    tags = []
    for srv in (primary, other):
        v = srv.store.find_volume(vid)
        with v._lock:
            v._sync_buffers()
        nv = v.nm.get(f.key)
        off = _types.stored_to_actual_offset(nv.offset)
        with open(v.file_name() + ".dat", "r+b") as fh:
            fh.seek(off + _types.NEEDLE_HEADER_SIZE + nv.size
                    + _types.NEEDLE_CHECKSUM_SIZE)
            fh.write(same_ns.to_bytes(8, "big"))
        n = v.read_needle(f.key)
        assert n.append_at_ns == same_ns
        assert n.replica_epoch() is not None, \
            "conflicting write carries no causality tag"
        tags.append(n.replica_epoch())
    assert tags[0] != tags[1]

    # readers during the heal: zero errors, always one of the variants
    errs, stop_readers = [], _threading.Event()

    def reader(addr):
        while not stop_readers.is_set():
            try:
                got = requests.get(f"http://{addr}/{fid}", timeout=30)
                assert got.status_code == 200
                assert got.content in (v2a, v2b)
            except BaseException:
                import traceback

                errs.append(traceback.format_exc())
                return

    ths = [_threading.Thread(target=reader, args=(v.address,))
           for v in volumes]
    for t in ths:
        t.start()
    try:
        report = primary.scrubber.run_once(vid=vid)
    finally:
        stop_readers.set()
        for t in ths:
            t.join()
    assert not errs, errs[0]

    # the conflict resolved WITHOUT an operator-facing failure
    div = [x for x in report.findings if x.kind == "replica_divergence"]
    assert div, "divergence never detected"
    assert all(x.state == "repaired" for x in div), \
        [(x.state, x.detail) for x in div]

    # both replicas converged on the SAME winner, deterministically
    got_a = requests.get(f"http://{primary.address}/{fid}", timeout=30)
    got_b = requests.get(f"http://{other.address}/{fid}", timeout=30)
    assert got_a.content == got_b.content
    assert got_a.content in (v2a, v2b)
    digests = set()
    for srv in volumes:
        stub = rpc.volume_stub(rpc.grpc_address(srv.address))
        d = stub.VolumeDigest(scrub_pb2.VolumeDigestRequest(volume_id=vid),
                              timeout=30)
        digests.add((d.rolling_crc, d.needle_count))
    assert len(digests) == 1, f"replicas still diverge: {digests}"


# -- ISSUE 15: replica-delete divergence is loud ----------------------------

def test_replica_delete_failure_is_counted_not_swallowed(tmp_path):
    """Regression for a real SWFS004 finding: the replica delete
    fan-out swallowed every failure bare (`except Exception: pass`), so
    a peer that missed the delete silently kept serving the live needle
    until anti-entropy noticed. The leg now retries through utils.retry
    and a final failure logs + counts
    `SeaweedFS_volume_replica_delete_failures` — while the delete still
    acks 202 (the local tombstone is durable; tombstone-wins anti-
    entropy converges the peer when it returns)."""
    from seaweedfs_tpu.utils.stats import VOLUME_REPLICA_DELETE_FAILURES

    old_native = os.environ.get("SEAWEEDFS_TPU_NATIVE")
    os.environ["SEAWEEDFS_TPU_NATIVE"] = "0"
    mport = _free_port()
    master = MasterServer(ip="localhost", port=mport,
                          volume_size_limit_mb=64)
    master.start(vacuum_interval=3600)
    volumes = []
    for i in range(2):
        vsrv = VolumeServer(directories=[str(tmp_path / f"dvol{i}")],
                            master=f"localhost:{mport}", ip="localhost",
                            port=_free_port(), pulse_seconds=1,
                            max_volume_counts=[16])
        vsrv.start()
        volumes.append(vsrv)
    fsrv = FilerServer(ip="localhost", port=_free_port(),
                       master=f"localhost:{mport}",
                       store_dir=str(tmp_path / "dfiler"),
                       chunk_size=32 * 1024, replication="001")
    fsrv.start()
    try:
        deadline = time.time() + 10
        while time.time() < deadline and len(master.topo.nodes) < 2:
            time.sleep(0.05)
        base = f"http://localhost:{fsrv.port}"
        _put_replicated(fsrv, base, "/chaos/deleted.bin",
                        os.urandom(2048))
        fid = fsrv.filer.find_entry("/chaos/deleted.bin") \
            .chunks[0].file_id
        vid = parse_file_id(fid).volume_id
        # the server that HOLDS the volume fans the delete out to its
        # peer; kill the peer so every retry of that leg fails
        primary = next(v for v in volumes
                       if v.store.find_volume(vid) is not None)
        peer = next(v for v in volumes if v is not primary)
        before = VOLUME_REPLICA_DELETE_FAILURES.value()
        # kill ONLY the peer's HTTP plane: a graceful stop() would
        # unregister it from the master and the fan-out would simply
        # skip it — the hazard is a peer that is REGISTERED but not
        # answering, which is what a crashed process looks like.
        # server_close() drops the listener (refused dials) and the
        # shared keep-alive pool is cleared so a warm connection from
        # the PUT can't keep the "dead" peer reachable
        from seaweedfs_tpu.wdclient import pool as _pool

        peer._http_server.shutdown()
        peer._http_server.server_close()
        _pool.POOL.clear()
        r = requests.delete(f"http://{primary.address}/{fid}",
                            timeout=60)
        assert r.status_code == 202, r.text
        # the failure was COUNTED (and logged), not swallowed
        assert VOLUME_REPLICA_DELETE_FAILURES.value() >= before + 1
        # and the local tombstone really landed
        assert requests.get(f"http://{primary.address}/{fid}",
                            timeout=10).status_code == 404
    finally:
        fsrv.stop()
        for v in volumes:
            try:
                v.stop()
            except Exception:
                pass
        master.stop()
        rpc.reset_channels()
        if old_native is None:
            os.environ.pop("SEAWEEDFS_TPU_NATIVE", None)
        else:
            os.environ["SEAWEEDFS_TPU_NATIVE"] = old_native
