"""In-process fake Cassandra: enough of the CQL binary protocol v4
(STARTUP/READY, PasswordAuthenticator challenge, QUERY with bound
values, Rows/Void results, ERROR frames) to exercise the real
cassandra filer store (seaweedfs_tpu/filer/stores/cql_wire.py) end to
end. Statements execute on sqlite with the CQL-isms translated
(USING TTL, keyspaces, clustering clauses)."""

from __future__ import annotations

import re
import socket
import sqlite3
import struct
import threading

OP_ERROR, OP_STARTUP, OP_READY, OP_AUTHENTICATE = 0x00, 0x01, 0x02, 0x03
OP_QUERY, OP_RESULT, OP_AUTH_RESPONSE, OP_AUTH_SUCCESS = (
    0x07, 0x08, 0x0F, 0x10)
T_BLOB, T_INT, T_VARCHAR = 0x0003, 0x0009, 0x000D


def translate_cql(cql: str) -> str | None:
    """CQL -> sqlite; None means 'acknowledge with Void, no-op'."""
    s = cql.strip()
    if re.match(r"CREATE KEYSPACE|USE\s", s, flags=re.I):
        return None
    s = re.sub(r"\s*USING TTL \?", "", s, flags=re.I)
    # CQL INSERT is an upsert by definition
    s = re.sub(r"^INSERT INTO", "INSERT OR REPLACE INTO", s, flags=re.I)
    s = re.sub(r"PRIMARY KEY\s*\(\((\w+)\),\s*(\w+)\)",
               r"PRIMARY KEY (\1, \2)", s, flags=re.I)
    s = re.sub(r"\)\s*WITH CLUSTERING ORDER BY.*$", ")", s,
               flags=re.I | re.S)
    s = s.replace("varchar", "TEXT").replace("blob", "BLOB")
    return s


class FakeCassandraServer:
    def __init__(self, *, username: str = "", password: str = ""):
        self.username, self.password = username, password
        self.db = sqlite3.connect(":memory:", check_same_thread=False)
        self._dblock = threading.Lock()
        self._listen = socket.socket()
        self._listen.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listen.bind(("localhost", 0))
        self._listen.listen(8)
        self.port = self._listen.getsockname()[1]
        self._stop = threading.Event()
        threading.Thread(target=self._accept_loop, daemon=True).start()

    def stop(self) -> None:
        self._stop.set()
        try:
            self._listen.close()
        except OSError:
            pass

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._listen.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    @staticmethod
    def _recv_exact(conn: socket.socket, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = conn.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("client gone")
            buf += chunk
        return buf

    @staticmethod
    def _frame(opcode: int, body: bytes, stream: int = 0) -> bytes:
        return struct.pack(">BBhBI", 0x84, 0, stream, opcode,
                           len(body)) + body

    def _error(self, code: int, msg: str) -> bytes:
        raw = msg.encode()
        return self._frame(OP_ERROR, struct.pack(">i", code)
                           + struct.pack(">H", len(raw)) + raw)

    def _serve(self, conn: socket.socket) -> None:
        try:
            authed = not self.password
            while not self._stop.is_set():
                head = self._recv_exact(conn, 9)
                _ver, _flags, stream, opcode, length = struct.unpack(
                    ">BBhBI", head)
                body = self._recv_exact(conn, length)
                if opcode == OP_STARTUP:
                    if self.password:
                        cls = "org.apache.cassandra.auth.PasswordAuthenticator"
                        raw = cls.encode()
                        conn.sendall(self._frame(
                            OP_AUTHENTICATE,
                            struct.pack(">H", len(raw)) + raw, stream))
                    else:
                        conn.sendall(self._frame(OP_READY, b"", stream))
                elif opcode == OP_AUTH_RESPONSE:
                    (n,) = struct.unpack(">i", body[:4])
                    token = body[4:4 + n]
                    parts = token.split(b"\x00")
                    if (len(parts) >= 3
                            and parts[1].decode() == self.username
                            and parts[2].decode() == self.password):
                        authed = True
                        conn.sendall(self._frame(
                            OP_AUTH_SUCCESS, struct.pack(">i", -1), stream))
                    else:
                        conn.sendall(self._error(0x0100, "Bad credentials"))
                elif opcode == OP_QUERY:
                    if not authed:
                        conn.sendall(self._error(0x0100, "not authed"))
                        continue
                    conn.sendall(self._query(body, stream))
                else:
                    conn.sendall(self._error(0x000A,
                                             f"bad opcode {opcode}"))
        except (ConnectionError, OSError, struct.error):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    # -- query handling ----------------------------------------------------

    def _query(self, body: bytes, stream: int) -> bytes:
        (qlen,) = struct.unpack(">I", body[:4])
        cql = body[4:4 + qlen].decode("utf-8")
        off = 4 + qlen
        _consistency, flags = struct.unpack_from(">HB", body, off)
        off += 3
        raw_vals: list[bytes | None] = []
        if flags & 0x01:
            (nvals,) = struct.unpack_from(">H", body, off)
            off += 2
            for _ in range(nvals):
                (ln,) = struct.unpack_from(">i", body, off)
                off += 4
                if ln < 0:
                    raw_vals.append(None)
                else:
                    raw_vals.append(body[off:off + ln])
                    off += ln
        had_ttl = re.search(r"USING TTL \?", cql, flags=re.I) is not None
        lite = translate_cql(cql)
        if lite is None:
            return self._frame(OP_RESULT, struct.pack(">i", 1), stream)
        if had_ttl and raw_vals:
            raw_vals = raw_vals[:-1]          # TTL param consumed
        # type the raw values by statement shape: INSERT binds
        # (text, text, blob); everything else binds text (LIMIT ? is
        # a 4-byte int, detected by context position)
        args: list = []
        is_insert = lite.lstrip().upper().startswith("INSERT")
        has_limit = re.search(r"LIMIT \?", lite, flags=re.I) is not None
        for i, rv in enumerate(raw_vals):
            if rv is None:
                args.append(None)
            elif is_insert and i == 2:
                args.append(rv)               # meta blob
            elif has_limit and i == len(raw_vals) - 1:
                args.append(int.from_bytes(rv, "big", signed=True))
            else:
                args.append(rv.decode("utf-8"))
        try:
            with self._dblock:
                cur = self.db.cursor()
                cur.execute(lite, args)
                rows = cur.fetchall() if cur.description else []
                desc = cur.description
                self.db.commit()
        except sqlite3.Error as e:
            return self._error(0x2200, f"sqlite: {e}")
        if not desc:
            return self._frame(OP_RESULT, struct.pack(">i", 1), stream)
        # Rows result with global_tables_spec
        types = []
        for ci in range(len(desc)):
            tid = T_VARCHAR
            for row in rows:
                v = row[ci]
                if v is None:
                    continue
                tid = (T_BLOB if isinstance(v, bytes)
                       else T_INT if isinstance(v, int) else T_VARCHAR)
                break
            types.append(tid)
        out = [struct.pack(">i", 2), struct.pack(">ii", 0x0001, len(desc))]

        def s(x: str) -> bytes:
            raw = x.encode()
            return struct.pack(">H", len(raw)) + raw

        out += [s("seaweedfs"), s("filemeta")]
        for col, tid in zip(desc, types):
            out.append(s(col[0]) + struct.pack(">H", tid))
        out.append(struct.pack(">i", len(rows)))
        for row in rows:
            for v, tid in zip(row, types):
                if v is None:
                    out.append(struct.pack(">i", -1))
                elif tid == T_INT:
                    out.append(struct.pack(">i", 4)
                               + struct.pack(">i", int(v)))
                elif isinstance(v, bytes):
                    out.append(struct.pack(">i", len(v)) + v)
                else:
                    raw = str(v).encode()
                    out.append(struct.pack(">i", len(raw)) + raw)
        return self._frame(OP_RESULT, b"".join(out), stream)
