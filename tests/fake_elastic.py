"""In-process fake Elasticsearch: enough of the REST API (document
PUT/GET/DELETE, index create/delete, _search with bool/term/range/
prefix queries, sort, size, search_after, basic auth) to exercise the
real elastic filer store (seaweedfs_tpu/filer/stores/elastic_wire.py)
end to end. Runs on http.server; JSON shapes mirror ES 7.x."""

from __future__ import annotations

import base64
import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


class FakeElasticServer:
    def __init__(self, *, username: str = "", password: str = ""):
        self.username, self.password = username, password
        # indices: name -> {doc_id: source}
        self.indices: dict[str, dict[str, dict]] = {}
        self._lock = threading.Lock()
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # quiet
                pass

            def _body(self) -> dict:
                n = int(self.headers.get("Content-Length") or 0)
                raw = self.rfile.read(n) if n else b""
                return json.loads(raw) if raw else {}

            def _send(self, status: int, doc: dict) -> None:
                payload = json.dumps(doc).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def _authed(self) -> bool:
                if not outer.password:
                    return True
                hdr = self.headers.get("Authorization", "")
                want = "Basic " + base64.b64encode(
                    f"{outer.username}:{outer.password}".encode()).decode()
                return hdr == want

            def _route(self, method: str) -> None:
                if not self._authed():
                    self._send(401, {"error": "unauthorized"})
                    return
                try:
                    outer._handle(self, method)
                except Exception as e:  # pragma: no cover
                    self._send(500, {"error": str(e)})

            def do_GET(self):
                self._route("GET")

            def do_PUT(self):
                self._route("PUT")

            def do_POST(self):
                self._route("POST")

            def do_DELETE(self):
                self._route("DELETE")

        self._httpd = ThreadingHTTPServer(("localhost", 0), Handler)
        self.port = self._httpd.server_port
        threading.Thread(target=self._httpd.serve_forever,
                         daemon=True).start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()

    # -- request handling --------------------------------------------------

    def _handle(self, h, method: str) -> None:
        path = h.path.split("?", 1)[0]
        parts = [p for p in path.split("/") if p]
        body = h._body() if method in ("PUT", "POST") else {}
        with self._lock:
            if len(parts) == 1:
                index = parts[0]
                if method == "PUT":       # create index
                    if index in self.indices:
                        h._send(400, {"error": {"type":
                                                "resource_already_exists"}})
                    else:
                        self.indices[index] = {}
                        h._send(200, {"acknowledged": True})
                elif method == "DELETE":
                    if self.indices.pop(index, None) is None:
                        h._send(404, {"error": "no such index"})
                    else:
                        h._send(200, {"acknowledged": True})
                else:
                    h._send(404, {"error": "bad request"})
                return
            if len(parts) == 2 and parts[1] == "_search":
                self._search(h, parts[0], body)
                return
            if len(parts) == 2 and parts[1] == "_refresh":
                h._send(200 if parts[0] in self.indices else 404,
                        {"_shards": {"successful": 1}})
                return
            if len(parts) == 3 and parts[1] == "_doc":
                index, doc_id = parts[0], parts[2]
                if method == "PUT":
                    self.indices.setdefault(index, {})[doc_id] = body
                    h._send(201, {"result": "created", "_id": doc_id})
                elif method == "GET":
                    docs = self.indices.get(index)
                    if docs is None:
                        h._send(404, {"error": "no such index",
                                      "found": False})
                    elif doc_id in docs:
                        h._send(200, {"found": True, "_id": doc_id,
                                      "_source": docs[doc_id]})
                    else:
                        h._send(404, {"found": False})
                elif method == "DELETE":
                    docs = self.indices.get(index)
                    if docs is None or doc_id not in docs:
                        h._send(404, {"result": "not_found"})
                    else:
                        del docs[doc_id]
                        h._send(200, {"result": "deleted"})
                return
        h._send(400, {"error": f"unhandled route {method} {path}"})

    # -- search ------------------------------------------------------------

    @staticmethod
    def _match_clause(clause: dict, src: dict) -> bool:
        kind = next(iter(clause))
        spec = clause[kind]
        field, cond = next(iter(spec.items()))
        value = src.get(field)
        if kind == "term":
            return value == cond
        if kind == "prefix":
            return isinstance(value, str) and value.startswith(cond)
        if kind == "range":
            for op, rhs in cond.items():
                if op == "gt" and not (value or "") > rhs:
                    return False
                if op == "gte" and not (value or "") >= rhs:
                    return False
                if op == "lt" and not (value or "") < rhs:
                    return False
                if op == "lte" and not (value or "") <= rhs:
                    return False
            return True
        raise ValueError(f"unsupported query clause {kind}")

    def _search(self, h, index: str, body: dict) -> None:
        docs = self.indices.get(index)
        if docs is None:
            h._send(404, {"error": "no such index"})
            return
        query = body.get("query", {})
        clauses = (query.get("bool", {}).get("must", [query])
                   if "bool" in query else [query] if query else [])
        rows = [(doc_id, src) for doc_id, src in docs.items()
                if all(self._match_clause(c, src) for c in clauses)]
        sort_spec = body.get("sort", [])
        sort_fields = []
        for s in sort_spec:
            if isinstance(s, dict):
                f, d = next(iter(s.items()))
                sort_fields.append((f, d if isinstance(d, str)
                                    else d.get("order", "asc")))
        for f, order in reversed(sort_fields):
            key = (lambda r, f=f: r[1].get(f) if f != "_id" else r[0])
            rows.sort(key=lambda r: key(r) or "", reverse=order == "desc")
        after = body.get("search_after")
        if after and sort_fields:
            f0 = sort_fields[0][0]

            def sort_val(r):
                return r[0] if f0 == "_id" else (r[1].get(f0) or "")

            rows = [r for r in rows if sort_val(r) > after[0]]
        size = body.get("size", 10)
        rows = rows[:size]
        hits = [{"_id": doc_id, "_source": src,
                 "sort": [src.get(sort_fields[0][0]) if sort_fields
                          and sort_fields[0][0] != "_id" else doc_id]}
                for doc_id, src in rows]
        h._send(200, {"hits": {"total": {"value": len(hits)},
                               "hits": hits}})
