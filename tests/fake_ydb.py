"""In-process fake YDB: Ydb.Table.V1.TableService over the real
ydb-api-protos wire shapes — sessions, the Operation/Any response
envelope, TypedValue parameters, struct-row ResultSets. It recognizes
the six YQL statement shapes the filer store issues (the reference's
ydb_queries.go verbatim), VALIDATES every declared parameter's type
tree (Int64 / Utf8 / String / Optional<Uint32> / Uint64 — a
wrong-typed or missing parameter errors like a real server), and
executes them against an in-memory (dir_hash, name) -> row dict with
ORDER BY/LIKE/LIMIT semantics implemented independently. Unknown
sessions answer BAD_SESSION; unknown statements GENERIC_ERROR.
"""

from __future__ import annotations

import re
import threading

from seaweedfs_tpu.pb import rpc
from seaweedfs_tpu.pb import ydb_operation_pb2 as O
from seaweedfs_tpu.pb import ydb_table_pb2 as T
from seaweedfs_tpu.pb import ydb_value_pb2 as V

RESULT_PAGE = 1000  # a real server truncates result sets; keep it small
# enough to matter only for huge listings, big enough for tests


def _op_ok(result_msg=None) -> O.Operation:
    op = O.Operation(ready=True, status=O.SUCCESS, id="fake-op")
    if result_msg is not None:
        op.result.Pack(result_msg)
    return op


def _op_err(status, message) -> O.Operation:
    return O.Operation(ready=True, status=status,
                       issues=[O.IssueMessage(message=message,
                                              severity=1)])


def _norm(yql: str) -> str:
    return re.sub(r"\s+", " ", yql).strip()


def _like_regex(pattern: str, escape: str) -> re.Pattern:
    """SQL LIKE pattern -> compiled regex ('%' any run, '_' any one
    char, `escape`-prefixed chars literal)."""
    out = []
    i = 0
    while i < len(pattern):
        c = pattern[i]
        if escape and c == escape and i + 1 < len(pattern):
            out.append(re.escape(pattern[i + 1]))
            i += 2
            continue
        if c == "%":
            out.append(".*")
        elif c == "_":
            out.append(".")
        else:
            out.append(re.escape(c))
        i += 1
    return re.compile("".join(out), re.DOTALL)


class _Expect:
    INT64 = ("int64",)
    UTF8 = ("utf8",)
    STRING = ("string",)
    UINT64 = ("uint64",)
    OPT_UINT32 = ("optional", "uint32")


_PARAM_SPECS = {
    "upsert": {"$dir_hash": _Expect.INT64, "$directory": _Expect.UTF8,
               "$name": _Expect.UTF8, "$meta": _Expect.STRING,
               "$expire_at": _Expect.OPT_UINT32},
    "delete": {"$dir_hash": _Expect.INT64, "$name": _Expect.UTF8},
    "find": {"$dir_hash": _Expect.INT64, "$name": _Expect.UTF8},
    "delete_children": {"$dir_hash": _Expect.INT64,
                        "$directory": _Expect.UTF8},
    "list": {"$dir_hash": _Expect.INT64, "$directory": _Expect.UTF8,
             "$start_name": _Expect.UTF8, "$prefix": _Expect.UTF8,
             "$limit": _Expect.UINT64},
}

_PRIMS = {V.Type.INT64: "int64", V.Type.UTF8: "utf8",
          V.Type.STRING: "string", V.Type.UINT64: "uint64",
          V.Type.UINT32: "uint32"}


def _type_shape(t: V.Type) -> tuple:
    if t.HasField("optional_type"):
        return ("optional",) + _type_shape(t.optional_type.item)
    return (_PRIMS.get(t.type_id, f"?{t.type_id}"),)


def _pyval(tv: V.TypedValue):
    v = tv.value
    which = v.WhichOneof("value")
    if which == "null_flag_value":
        return None
    return getattr(v, which)


class _TableServicer:
    def __init__(self):
        self.lock = threading.Lock()
        self.sessions: set[str] = set()
        self._next_session = 0
        self.tables: set[str] = set()
        # (dir_hash, name) -> (directory, meta, expire_at)
        self.rows: dict[tuple[int, str], tuple[str, bytes, int | None]] = {}
        self.queries: list[str] = []  # observed, for tests

    # -- service methods ---------------------------------------------------

    def CreateSession(self, req: T.CreateSessionRequest, _):
        with self.lock:
            self._next_session += 1
            sid = f"fake-session-{self._next_session}"
            self.sessions.add(sid)
        return T.CreateSessionResponse(
            operation=_op_ok(T.CreateSessionResult(session_id=sid)))

    def DeleteSession(self, req: T.DeleteSessionRequest, _):
        with self.lock:
            self.sessions.discard(req.session_id)
        return T.DeleteSessionResponse(operation=_op_ok())

    def ExecuteSchemeQuery(self, req: T.ExecuteSchemeQueryRequest, _):
        bad = self._check_session(req.session_id)
        if bad:
            return T.ExecuteSchemeQueryResponse(operation=bad)
        q = _norm(req.yql_text)
        m = re.search(r"CREATE TABLE (\w+)", q)
        if not m:
            return T.ExecuteSchemeQueryResponse(operation=_op_err(
                O.GENERIC_ERROR, f"unsupported scheme query: {q[:80]}"))
        with self.lock:
            if m.group(1) in self.tables:
                return T.ExecuteSchemeQueryResponse(operation=_op_err(
                    O.SCHEME_ERROR, "table already exists"))
            self.tables.add(m.group(1))
        return T.ExecuteSchemeQueryResponse(operation=_op_ok())

    def ExecuteDataQuery(self, req: T.ExecuteDataQueryRequest, _):
        bad = self._check_session(req.session_id)
        if bad:
            return T.ExecuteDataQueryResponse(operation=bad)
        kind = self._classify(req.query.yql_text)
        if kind is None:
            return T.ExecuteDataQueryResponse(operation=_op_err(
                O.GENERIC_ERROR,
                f"unrecognized statement: {_norm(req.query.yql_text)[:80]}"))
        err = self._check_params(kind.split(":")[0], req.parameters)
        if err:
            return T.ExecuteDataQueryResponse(operation=_op_err(
                O.BAD_REQUEST, err))
        self.queries.append(kind)
        p = {k: _pyval(tv) for k, tv in req.parameters.items()}
        with self.lock:
            result = self._run(kind, p)
        return T.ExecuteDataQueryResponse(operation=_op_ok(result))

    # -- internals ---------------------------------------------------------

    def _check_session(self, sid: str):
        with self.lock:
            if sid not in self.sessions:
                return _op_err(O.BAD_SESSION, f"unknown session {sid!r}")
        return None

    @staticmethod
    def _classify(yql: str) -> str | None:
        q = _norm(yql)
        if "UPSERT INTO filemeta" in q:
            return "upsert"
        if q.startswith("PRAGMA") and "DELETE FROM filemeta" in q:
            if "$directory" in q:
                return "delete_children"
            return "delete"
        if "SELECT meta FROM filemeta" in q:
            return "find"
        if "SELECT name, meta FROM filemeta" in q:
            kind = None
            if "name >= $start_name" in q:
                kind = "list:inclusive"
            elif "name > $start_name" in q:
                kind = "list:exclusive"
            if kind and "ESCAPE '!'" in q:
                kind += ":escape"
            return kind
        return None

    @staticmethod
    def _check_params(kind: str, params) -> str | None:
        spec = _PARAM_SPECS[kind]
        got = set(params.keys())
        if got != set(spec):
            return f"parameters mismatch: got {sorted(got)}"
        for name, want in spec.items():
            shape = _type_shape(params[name].type)
            if shape != want:
                return f"{name}: declared {want}, got {shape}"
        return None

    def _run(self, kind: str, p: dict):
        if kind == "upsert":
            self.rows[(p["$dir_hash"], p["$name"])] = (
                p["$directory"], p["$meta"], p["$expire_at"])
            return T.ExecuteQueryResult()
        if kind == "delete":
            self.rows.pop((p["$dir_hash"], p["$name"]), None)
            return T.ExecuteQueryResult()
        if kind == "delete_children":
            doomed = [k for k, (d, _, _) in self.rows.items()
                      if k[0] == p["$dir_hash"] and d == p["$directory"]]
            for k in doomed:
                del self.rows[k]
            return T.ExecuteQueryResult()
        if kind == "find":
            rs = V.ResultSet(columns=[V.Column(
                name="meta", type=V.Type(type_id=V.Type.STRING))])
            row = self.rows.get((p["$dir_hash"], p["$name"]))
            if row is not None:
                rs.rows.append(V.Value(items=[
                    V.Value(bytes_value=row[1])]))
            return T.ExecuteQueryResult(result_sets=[rs])
        # list — real LIKE semantics: '%'/'_' are wildcards unless the
        # statement declares ESCAPE '!' and the char is escaped (a
        # literal-startswith fake would mask the wildcard-prefix bug the
        # store must defend against)
        inclusive = "inclusive" in kind
        escape = "!" if kind.endswith("escape") else ""
        matcher = _like_regex(p["$prefix"], escape)
        names = sorted(
            n for (h, n), (d, _, _) in self.rows.items()
            if h == p["$dir_hash"] and d == p["$directory"]
            and (n >= p["$start_name"] if inclusive
                 else n > p["$start_name"])
            and matcher.fullmatch(n))
        # truncated reflects the RESULT-SET CAP only: a LIMIT-bounded
        # page is a COMPLETED query on a real server (truncated=False
        # even when more rows match). A fake that set truncated for
        # LIMIT-bounding too would hide the wildcard-prefix under-return
        # the store's paging loop must survive (ADVICE r5 #1).
        limit = min(p["$limit"], RESULT_PAGE)
        truncated = p["$limit"] > RESULT_PAGE and len(names) > RESULT_PAGE
        rs = V.ResultSet(
            columns=[V.Column(name="name",
                              type=V.Type(type_id=V.Type.UTF8)),
                     V.Column(name="meta",
                              type=V.Type(type_id=V.Type.STRING))],
            truncated=truncated)
        for n in names[:limit]:
            meta = self.rows[(p["$dir_hash"], n)][1]
            rs.rows.append(V.Value(items=[V.Value(text_value=n),
                                          V.Value(bytes_value=meta)]))
        return T.ExecuteQueryResult(result_sets=[rs])


class FakeYdbServer:
    def __init__(self):
        self.servicer = _TableServicer()
        self._server = rpc.new_server(max_workers=8)
        rpc.add_servicer(self._server, rpc.ydb_table_service(),
                         self.servicer)
        self.port = self._server.add_insecure_port("localhost:0")
        self._server.start()

    @property
    def rows(self):
        return self.servicer.rows

    def expire_sessions(self) -> None:
        """Simulate server-side session loss (store must recreate)."""
        with self.servicer.lock:
            self.servicer.sessions.clear()

    def stop(self) -> None:
        self._server.stop(grace=0.2)
