"""Coverage for the round-2 proto surface: the mount/s3/iam/mq/remote
services (/root/reference/weed/pb/{mount,s3,iam,mq,remote}.proto) and the
four volume RPCs the round-1 build lacked (ReadNeedleMeta,
FetchAndWriteNeedle, Query, VolumeNeedleStatus —
/root/reference/weed/pb/volume_server.proto:59,103,107,110)."""

import json
import socket
import time

import pytest

from seaweedfs_tpu.operation import assign, upload_data
from seaweedfs_tpu.pb import (
    mq_pb2,
    remote_pb2,
    rpc,
    s3_pb2,
    volume_server_pb2 as vs,
)
from seaweedfs_tpu.server.master import MasterServer
from seaweedfs_tpu.server.volume import VolumeServer
from seaweedfs_tpu.storage.file_id import parse_file_id


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    mport = _free_port()
    master = MasterServer(ip="localhost", port=mport, volume_size_limit_mb=64)
    master.start(vacuum_interval=3600)
    vsrv = VolumeServer(directories=[str(tmp_path_factory.mktemp("vol"))],
                        master=f"localhost:{mport}", ip="localhost",
                        port=_free_port(), pulse_seconds=1)
    vsrv.start()
    deadline = time.time() + 10
    while time.time() < deadline and not master.topo.nodes:
        time.sleep(0.05)
    yield master, vsrv
    vsrv.stop()
    master.stop()
    rpc.reset_channels()


def _put(master, payload: bytes, mime="application/octet-stream"):
    a = assign(master.address)
    assert not a.error
    r = upload_data(f"http://{a.url}/{a.fid}", payload, mime=mime)
    assert not r.error
    return a


# -- ReadNeedleMeta / VolumeNeedleStatus ------------------------------------

def test_needle_meta_and_status(cluster):
    master, vsrv = cluster
    payload = b"needle-meta-payload" * 10
    a = _put(master, payload)
    f = parse_file_id(a.fid)
    stub = rpc.volume_stub(rpc.grpc_address(vsrv.address))

    st = stub.VolumeNeedleStatus(vs.VolumeNeedleStatusRequest(
        volume_id=f.volume_id, needle_id=f.key), timeout=10)
    assert st.needle_id == f.key
    assert st.cookie == f.cookie
    assert st.size > 0 and st.crc != 0

    meta = stub.ReadNeedleMeta(vs.ReadNeedleMetaRequest(
        volume_id=f.volume_id, needle_id=f.key), timeout=10)
    assert meta.cookie == f.cookie
    assert meta.crc == st.crc
    assert meta.last_modified > 0

    import grpc as _grpc

    with pytest.raises(_grpc.RpcError):
        stub.VolumeNeedleStatus(vs.VolumeNeedleStatusRequest(
            volume_id=f.volume_id, needle_id=0xDEAD), timeout=10)


# -- FetchAndWriteNeedle ----------------------------------------------------

def test_fetch_and_write_needle(cluster, tmp_path):
    import requests

    master, vsrv = cluster
    remote_root = tmp_path / "remote"
    remote_root.mkdir()
    (remote_root / "obj.bin").write_bytes(b"remote object body")

    a = _put(master, b"placeholder")  # ensures a writable volume exists
    f = parse_file_id(a.fid)
    stub = rpc.volume_stub(rpc.grpc_address(vsrv.address))
    stub.FetchAndWriteNeedle(vs.FetchAndWriteNeedleRequest(
        volume_id=f.volume_id, needle_id=0x77, cookie=0x1234,
        remote_conf=remote_pb2.RemoteConf(type="local",
                                          local_root=str(remote_root)),
        remote_location=remote_pb2.RemoteStorageLocation(path="/obj.bin"),
    ), timeout=10)

    r = requests.get(f"http://{vsrv.address}/{f.volume_id},7700001234",
                     timeout=10)
    assert r.status_code == 200
    assert r.content == b"remote object body"


# -- Query ------------------------------------------------------------------

def test_query_json_and_csv(cluster):
    master, vsrv = cluster
    docs = [{"name": "a", "n": 1}, {"name": "b", "n": 5}, {"name": "c", "n": 9}]
    a = _put(master, "\n".join(json.dumps(d) for d in docs).encode(),
             mime="application/json")
    stub = rpc.volume_stub(rpc.grpc_address(vsrv.address))

    req = vs.QueryRequest(from_file_ids=[a.fid], selections=["name"])
    req.filter.field, req.filter.operand, req.filter.value = "n", ">", "3"
    req.input_serialization.json_input.type = "LINES"
    stripes = list(stub.Query(req, timeout=10))
    got = [json.loads(line) for s in stripes
           for line in s.records.decode().splitlines() if line]
    assert got == [{"name": "b"}, {"name": "c"}]

    csv_body = b"name,n\nx,2\ny,8\n"
    b = _put(master, csv_body, mime="text/csv")
    req2 = vs.QueryRequest(from_file_ids=[b.fid])
    req2.filter.field, req2.filter.operand, req2.filter.value = "n", ">=", "8"
    req2.input_serialization.csv_input.file_header_info = "USE"
    req2.output_serialization.csv_output.field_delimiter = ","
    stripes2 = list(stub.Query(req2, timeout=10))
    assert stripes2 and b"y,8" in stripes2[0].records.replace(b"\r", b"")


# -- MQ gRPC plane ----------------------------------------------------------

def test_mq_grpc_publish_subscribe():
    from seaweedfs_tpu.mq import Broker
    from seaweedfs_tpu.mq.grpc_server import MqGrpcServer

    broker = Broker()
    port = _free_port()
    srv = MqGrpcServer(broker, port=port, address=f"localhost:{port}")
    srv.start()
    try:
        stub = rpc.mq_stub(f"localhost:{port}")
        lead = stub.FindBrokerLeader(
            mq_pb2.FindBrokerLeaderRequest(filer_group=""), timeout=5)
        assert lead.broker == f"localhost:{port}"

        seg = mq_pb2.Segment(namespace="ns", topic="events", id=0)
        assign_resp = stub.AssignSegmentBrokers(
            mq_pb2.AssignSegmentBrokersRequest(segment=seg), timeout=5)
        assert assign_resp.brokers == [f"localhost:{port}"]
        assert stub.CheckSegmentStatus(
            mq_pb2.CheckSegmentStatusRequest(segment=seg), timeout=5).is_active

        def feed():
            yield mq_pb2.PublishRequest(
                init=mq_pb2.PublishRequest.InitMessage(segment=seg))
            for i in range(5):
                yield mq_pb2.PublishRequest(key=b"k%d" % i,
                                            message=b"payload-%d" % i)

        acks = [r.ack_sequence for r in stub.Publish(feed(), timeout=10)]
        assert acks == [0, 1, 2, 3, 4]

        got = list(stub.Subscribe(mq_pb2.SubscribeRequest(
            segment=seg, start_offset=1, max_records=3), timeout=10))
        assert [g.offset for g in got] == [1, 2, 3]
        assert got[0].message == b"payload-1"

        load = stub.CheckBrokerLoad(mq_pb2.CheckBrokerLoadRequest(), timeout=5)
        assert load.message_count == 5 and load.bytes_count > 0
    finally:
        srv.stop()
        rpc.reset_channels()


# -- S3 Configure -----------------------------------------------------------

def test_s3_configure_grpc():
    from seaweedfs_tpu.s3api.server import S3Server

    port = _free_port()
    srv = S3Server(port=port, filer="localhost:1")  # filer never dialed here
    srv.start()
    try:
        conf = {"identities": [{
            "name": "ops",
            "credentials": [{"accessKey": "AK1", "secretKey": "SK1"}],
            "actions": ["Read", "Write:bucket1"],
        }]}
        stub = rpc.s3_stub(f"localhost:{rpc.derived_grpc_port(port)}")
        stub.Configure(s3_pb2.S3ConfigureRequest(
            s3_configuration_file_content=json.dumps(conf).encode()),
            timeout=5)
        ident = srv.iam.lookup("AK1")
        assert ident.name == "ops" and ident.secret_key == "SK1"
        assert ident.allows("Write", "bucket1")
        assert not ident.allows("Write", "bucket2")

        import grpc as _grpc

        with pytest.raises(_grpc.RpcError):
            stub.Configure(s3_pb2.S3ConfigureRequest(
                s3_configuration_file_content=b"{nope"), timeout=5)
    finally:
        srv.stop()
        rpc.reset_channels()


# -- Mount control ----------------------------------------------------------

def test_mount_configure_grpc():
    from seaweedfs_tpu.mount.control import MountControlServer
    from seaweedfs_tpu.mount.weedfs import WFS
    from seaweedfs_tpu.pb import mount_pb2

    wfs = WFS("localhost:1", subscribe=False)
    port = _free_port()
    srv = MountControlServer(wfs, port=port)
    srv.start()
    try:
        stub = rpc.mount_stub(f"localhost:{port}")
        stub.Configure(mount_pb2.ConfigureRequest(collection_capacity=12345),
                       timeout=5)
        assert wfs.collection_capacity == 12345
        # quota is enforced: once usage reaches capacity, writes ENOSPC
        class _FakeStub:
            def Statistics(self, req, timeout=0):
                from seaweedfs_tpu.pb import filer_pb2

                return filer_pb2.StatisticsResponse(used_size=20000)

        wfs.stub = _FakeStub()
        assert wfs._quota_exceeded()
        import errno as _errno

        with pytest.raises(OSError) as ei:
            wfs.write(1, 0, b"data")
        assert ei.value.errno == _errno.ENOSPC

        stub.Configure(mount_pb2.ConfigureRequest(collection_capacity=-1),
                       timeout=5)
        assert wfs.collection_capacity == 0
        assert not wfs._quota_exceeded()
    finally:
        srv.stop()
        rpc.reset_channels()


# -- remote_pb mapping ------------------------------------------------------

def test_remote_mapping_pb_roundtrip():
    from seaweedfs_tpu.remote_storage import conf_to_pb, mapping_to_pb

    conf = {"storages": {"src": {"type": "local", "root": "/tmp/r"},
                         "cloud": {"type": "s3", "endpoint": "http://s3:9000"}},
            "mounts": {"/data": {"storage": "cloud",
                                 "remote_path": "bucket1/sub/dir"},
                       "/arch": {"storage": "src",
                                 "remote_path": "archive/2024"}}}
    m = remote_pb2.RemoteStorageMapping()
    m.ParseFromString(mapping_to_pb(conf))
    # bucket-addressed backend: first segment is the bucket
    assert m.mappings["/data"].name == "cloud"
    assert m.mappings["/data"].bucket == "bucket1"
    assert m.mappings["/data"].path == "/sub/dir"
    # local backend: no bucket, full path preserved
    assert m.mappings["/arch"].name == "src"
    assert m.mappings["/arch"].bucket == ""
    assert m.mappings["/arch"].path == "/archive/2024"

    rc = remote_pb2.RemoteConf()
    rc.ParseFromString(conf_to_pb("src", conf["storages"]["src"]))
    assert rc.type == "local" and rc.local_root == "/tmp/r"


# -- round-5 proto parity: master vacuum/readonly/raft + filer stream rpcs --

def test_master_vacuum_toggle_grpc(cluster):
    """DisableVacuum/EnableVacuum (reference master.proto:30-32) pause
    and resume the periodic vacuum driver."""
    from seaweedfs_tpu.pb import master_pb2

    master, _ = cluster
    stub = rpc.master_stub(rpc.grpc_address(master.address))
    stub.DisableVacuum(master_pb2.DisableVacuumRequest(), timeout=10)
    assert master.vacuum_disabled is True
    stub.EnableVacuum(master_pb2.EnableVacuumRequest(), timeout=10)
    assert master.vacuum_disabled is False


@pytest.fixture
def fresh_cluster(tmp_path):
    """Function-scoped cluster with free volume slots (the module
    cluster's slots are exhausted by earlier tests)."""
    mport = _free_port()
    master = MasterServer(ip="localhost", port=mport,
                          volume_size_limit_mb=64)
    master.start(vacuum_interval=3600)
    vsrv = VolumeServer(directories=[str(tmp_path / "vol")],
                        master=f"localhost:{mport}", ip="localhost",
                        port=_free_port(), pulse_seconds=1)
    vsrv.start()
    deadline = time.time() + 10
    while time.time() < deadline and not master.topo.nodes:
        time.sleep(0.05)
    yield master, vsrv
    vsrv.stop()
    master.stop()


def test_master_volume_mark_readonly_grpc(fresh_cluster):
    """VolumeMarkReadonly (reference master.proto:34 /
    master_grpc_server_volume.go:301): the volume leaves the writable
    set so assignment skips it; marking writable restores it."""
    from seaweedfs_tpu.pb import master_pb2

    master, vsrv = fresh_cluster
    a = _put(master, b"mark me readonly")
    vid = parse_file_id(a.fid).volume_id
    stub = rpc.master_stub(rpc.grpc_address(master.address))
    stub.VolumeMarkReadonly(master_pb2.VolumeMarkReadonlyRequest(
        ip="localhost", port=vsrv.port, volume_id=vid,
        is_readonly=True), timeout=10)
    layouts = [vl for vl in master.topo.layouts.values()
               if vid in vl.locations]
    assert layouts and all(vid in vl.readonly and vid not in vl.writables
                           for vl in layouts)
    stub.VolumeMarkReadonly(master_pb2.VolumeMarkReadonlyRequest(
        ip="localhost", port=vsrv.port, volume_id=vid,
        is_readonly=False), timeout=10)
    assert all(vid not in vl.readonly for vl in layouts)
    # unknown volume -> NOT_FOUND
    import grpc as grpc_mod
    with pytest.raises(grpc_mod.RpcError) as ei:
        stub.VolumeMarkReadonly(master_pb2.VolumeMarkReadonlyRequest(
            volume_id=9999, is_readonly=True), timeout=10)
    assert ei.value.code() == grpc_mod.StatusCode.NOT_FOUND


def test_master_raft_list_single_master(cluster):
    """RaftListClusterServers in single-master mode: one Voter, leading
    (reference master.proto:46)."""
    from seaweedfs_tpu.pb import master_pb2

    master, _ = cluster
    resp = rpc.master_stub(rpc.grpc_address(master.address)) \
        .RaftListClusterServers(
            master_pb2.RaftListClusterServersRequest(), timeout=10)
    assert len(resp.cluster_servers) == 1
    s = resp.cluster_servers[0]
    assert s.address == master.address and s.isLeader


def test_filer_stream_rename_entry(fresh_cluster):
    """StreamRenameEntry (reference filer.proto:33): a directory move
    streams one rename event per moved entry, children first."""
    from seaweedfs_tpu.pb import filer_pb2
    from seaweedfs_tpu.server.filer import FilerServer

    master, _ = fresh_cluster
    fsrv = FilerServer(ip="localhost", port=_free_port(),
                       master=master.address, store="memory")
    fsrv.start()
    try:
        import requests

        for name in ("a.txt", "b.txt"):
            r = requests.put(f"http://{fsrv.address}/olddir/{name}",
                             data=name.encode(), timeout=10)
            assert r.status_code in (200, 201)
        stub = rpc.filer_stub(rpc.grpc_address(fsrv.address))
        events = list(stub.StreamRenameEntry(
            filer_pb2.StreamRenameEntryRequest(
                old_directory="/", old_name="olddir",
                new_directory="/", new_name="newdir",
                signatures=[1234]), timeout=30))
        # 2 children + the directory itself, children first
        assert len(events) == 3
        moved = [e.event_notification.new_entry.name for e in events]
        assert moved[-1] == "newdir" and set(moved[:-1]) == {"a.txt", "b.txt"}
        assert all(1234 in e.event_notification.signatures for e in events)
        assert all(e.ts_ns > 0 for e in events)
        # the move really happened
        g = requests.get(f"http://{fsrv.address}/newdir/a.txt", timeout=10)
        assert g.status_code == 200 and g.content == b"a.txt"
        assert requests.get(f"http://{fsrv.address}/olddir/a.txt",
                            timeout=10).status_code == 404
    finally:
        fsrv.stop()
