"""Coverage for the round-2 proto surface: the mount/s3/iam/mq/remote
services (/root/reference/weed/pb/{mount,s3,iam,mq,remote}.proto) and the
four volume RPCs the round-1 build lacked (ReadNeedleMeta,
FetchAndWriteNeedle, Query, VolumeNeedleStatus —
/root/reference/weed/pb/volume_server.proto:59,103,107,110)."""

import json
import socket
import time

import pytest

from seaweedfs_tpu.operation import assign, upload_data
from seaweedfs_tpu.pb import (
    mq_pb2,
    remote_pb2,
    rpc,
    s3_pb2,
    volume_server_pb2 as vs,
)
from seaweedfs_tpu.server.master import MasterServer
from seaweedfs_tpu.server.volume import VolumeServer
from seaweedfs_tpu.storage.file_id import parse_file_id


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    mport = _free_port()
    master = MasterServer(ip="localhost", port=mport, volume_size_limit_mb=64)
    master.start(vacuum_interval=3600)
    vsrv = VolumeServer(directories=[str(tmp_path_factory.mktemp("vol"))],
                        master=f"localhost:{mport}", ip="localhost",
                        port=_free_port(), pulse_seconds=1)
    vsrv.start()
    deadline = time.time() + 10
    while time.time() < deadline and not master.topo.nodes:
        time.sleep(0.05)
    yield master, vsrv
    vsrv.stop()
    master.stop()
    rpc.reset_channels()


def _put(master, payload: bytes, mime="application/octet-stream"):
    a = assign(master.address)
    assert not a.error
    r = upload_data(f"http://{a.url}/{a.fid}", payload, mime=mime)
    assert not r.error
    return a


# -- ReadNeedleMeta / VolumeNeedleStatus ------------------------------------

def test_needle_meta_and_status(cluster):
    master, vsrv = cluster
    payload = b"needle-meta-payload" * 10
    a = _put(master, payload)
    f = parse_file_id(a.fid)
    stub = rpc.volume_stub(rpc.grpc_address(vsrv.address))

    st = stub.VolumeNeedleStatus(vs.VolumeNeedleStatusRequest(
        volume_id=f.volume_id, needle_id=f.key), timeout=10)
    assert st.needle_id == f.key
    assert st.cookie == f.cookie
    assert st.size > 0 and st.crc != 0

    meta = stub.ReadNeedleMeta(vs.ReadNeedleMetaRequest(
        volume_id=f.volume_id, needle_id=f.key), timeout=10)
    assert meta.cookie == f.cookie
    assert meta.crc == st.crc
    assert meta.last_modified > 0

    import grpc as _grpc

    with pytest.raises(_grpc.RpcError):
        stub.VolumeNeedleStatus(vs.VolumeNeedleStatusRequest(
            volume_id=f.volume_id, needle_id=0xDEAD), timeout=10)


# -- FetchAndWriteNeedle ----------------------------------------------------

def test_fetch_and_write_needle(cluster, tmp_path):
    import requests

    master, vsrv = cluster
    remote_root = tmp_path / "remote"
    remote_root.mkdir()
    (remote_root / "obj.bin").write_bytes(b"remote object body")

    a = _put(master, b"placeholder")  # ensures a writable volume exists
    f = parse_file_id(a.fid)
    stub = rpc.volume_stub(rpc.grpc_address(vsrv.address))
    stub.FetchAndWriteNeedle(vs.FetchAndWriteNeedleRequest(
        volume_id=f.volume_id, needle_id=0x77, cookie=0x1234,
        remote_conf=remote_pb2.RemoteConf(type="local",
                                          local_root=str(remote_root)),
        remote_location=remote_pb2.RemoteStorageLocation(path="/obj.bin"),
    ), timeout=10)

    r = requests.get(f"http://{vsrv.address}/{f.volume_id},7700001234",
                     timeout=10)
    assert r.status_code == 200
    assert r.content == b"remote object body"


# -- Query ------------------------------------------------------------------

def test_query_json_and_csv(cluster):
    master, vsrv = cluster
    docs = [{"name": "a", "n": 1}, {"name": "b", "n": 5}, {"name": "c", "n": 9}]
    a = _put(master, "\n".join(json.dumps(d) for d in docs).encode(),
             mime="application/json")
    stub = rpc.volume_stub(rpc.grpc_address(vsrv.address))

    req = vs.QueryRequest(from_file_ids=[a.fid], selections=["name"])
    req.filter.field, req.filter.operand, req.filter.value = "n", ">", "3"
    req.input_serialization.json_input.type = "LINES"
    stripes = list(stub.Query(req, timeout=10))
    got = [json.loads(line) for s in stripes
           for line in s.records.decode().splitlines() if line]
    assert got == [{"name": "b"}, {"name": "c"}]

    csv_body = b"name,n\nx,2\ny,8\n"
    b = _put(master, csv_body, mime="text/csv")
    req2 = vs.QueryRequest(from_file_ids=[b.fid])
    req2.filter.field, req2.filter.operand, req2.filter.value = "n", ">=", "8"
    req2.input_serialization.csv_input.file_header_info = "USE"
    req2.output_serialization.csv_output.field_delimiter = ","
    stripes2 = list(stub.Query(req2, timeout=10))
    assert stripes2 and b"y,8" in stripes2[0].records.replace(b"\r", b"")


# -- MQ gRPC plane ----------------------------------------------------------

def test_mq_grpc_publish_subscribe():
    from seaweedfs_tpu.mq import Broker
    from seaweedfs_tpu.mq.grpc_server import MqGrpcServer

    broker = Broker()
    port = _free_port()
    srv = MqGrpcServer(broker, port=port, address=f"localhost:{port}")
    srv.start()
    try:
        stub = rpc.mq_stub(f"localhost:{port}")
        lead = stub.FindBrokerLeader(
            mq_pb2.FindBrokerLeaderRequest(filer_group=""), timeout=5)
        assert lead.broker == f"localhost:{port}"

        seg = mq_pb2.Segment(namespace="ns", topic="events", id=0)
        assign_resp = stub.AssignSegmentBrokers(
            mq_pb2.AssignSegmentBrokersRequest(segment=seg), timeout=5)
        assert assign_resp.brokers == [f"localhost:{port}"]
        assert stub.CheckSegmentStatus(
            mq_pb2.CheckSegmentStatusRequest(segment=seg), timeout=5).is_active

        def feed():
            yield mq_pb2.PublishRequest(
                init=mq_pb2.PublishRequest.InitMessage(segment=seg))
            for i in range(5):
                yield mq_pb2.PublishRequest(key=b"k%d" % i,
                                            message=b"payload-%d" % i)

        acks = [r.ack_sequence for r in stub.Publish(feed(), timeout=10)]
        assert acks == [0, 1, 2, 3, 4]

        got = list(stub.Subscribe(mq_pb2.SubscribeRequest(
            segment=seg, start_offset=1, max_records=3), timeout=10))
        assert [g.offset for g in got] == [1, 2, 3]
        assert got[0].message == b"payload-1"

        load = stub.CheckBrokerLoad(mq_pb2.CheckBrokerLoadRequest(), timeout=5)
        assert load.message_count == 5 and load.bytes_count > 0
    finally:
        srv.stop()
        rpc.reset_channels()


# -- S3 Configure -----------------------------------------------------------

def test_s3_configure_grpc():
    from seaweedfs_tpu.s3api.server import S3Server

    port = _free_port()
    srv = S3Server(port=port, filer="localhost:1")  # filer never dialed here
    srv.start()
    try:
        conf = {"identities": [{
            "name": "ops",
            "credentials": [{"accessKey": "AK1", "secretKey": "SK1"}],
            "actions": ["Read", "Write:bucket1"],
        }]}
        stub = rpc.s3_stub(f"localhost:{rpc.derived_grpc_port(port)}")
        stub.Configure(s3_pb2.S3ConfigureRequest(
            s3_configuration_file_content=json.dumps(conf).encode()),
            timeout=5)
        ident = srv.iam.lookup("AK1")
        assert ident.name == "ops" and ident.secret_key == "SK1"
        assert ident.allows("Write", "bucket1")
        assert not ident.allows("Write", "bucket2")

        import grpc as _grpc

        with pytest.raises(_grpc.RpcError):
            stub.Configure(s3_pb2.S3ConfigureRequest(
                s3_configuration_file_content=b"{nope"), timeout=5)
    finally:
        srv.stop()
        rpc.reset_channels()


# -- Mount control ----------------------------------------------------------

def test_mount_configure_grpc():
    from seaweedfs_tpu.mount.control import MountControlServer
    from seaweedfs_tpu.mount.weedfs import WFS
    from seaweedfs_tpu.pb import mount_pb2

    wfs = WFS("localhost:1", subscribe=False)
    port = _free_port()
    srv = MountControlServer(wfs, port=port)
    srv.start()
    try:
        stub = rpc.mount_stub(f"localhost:{port}")
        stub.Configure(mount_pb2.ConfigureRequest(collection_capacity=12345),
                       timeout=5)
        assert wfs.collection_capacity == 12345
        # quota is enforced: once usage reaches capacity, writes ENOSPC
        class _FakeStub:
            def Statistics(self, req, timeout=0):
                from seaweedfs_tpu.pb import filer_pb2

                return filer_pb2.StatisticsResponse(used_size=20000)

        wfs.stub = _FakeStub()
        assert wfs._quota_exceeded()
        import errno as _errno

        with pytest.raises(OSError) as ei:
            wfs.write(1, 0, b"data")
        assert ei.value.errno == _errno.ENOSPC

        stub.Configure(mount_pb2.ConfigureRequest(collection_capacity=-1),
                       timeout=5)
        assert wfs.collection_capacity == 0
        assert not wfs._quota_exceeded()
    finally:
        srv.stop()
        rpc.reset_channels()


# -- remote_pb mapping ------------------------------------------------------

def test_remote_mapping_pb_roundtrip():
    from seaweedfs_tpu.remote_storage import conf_to_pb, mapping_to_pb

    conf = {"storages": {"src": {"type": "local", "root": "/tmp/r"},
                         "cloud": {"type": "s3", "endpoint": "http://s3:9000"}},
            "mounts": {"/data": {"storage": "cloud",
                                 "remote_path": "bucket1/sub/dir"},
                       "/arch": {"storage": "src",
                                 "remote_path": "archive/2024"}}}
    m = remote_pb2.RemoteStorageMapping()
    m.ParseFromString(mapping_to_pb(conf))
    # bucket-addressed backend: first segment is the bucket
    assert m.mappings["/data"].name == "cloud"
    assert m.mappings["/data"].bucket == "bucket1"
    assert m.mappings["/data"].path == "/sub/dir"
    # local backend: no bucket, full path preserved
    assert m.mappings["/arch"].name == "src"
    assert m.mappings["/arch"].bucket == ""
    assert m.mappings["/arch"].path == "/archive/2024"

    rc = remote_pb2.RemoteConf()
    rc.ParseFromString(conf_to_pb("src", conf["storages"]["src"]))
    assert rc.type == "local" and rc.local_root == "/tmp/r"
