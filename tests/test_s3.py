"""S3 gateway conformance tests over a live in-process cluster
(the reference runs aws-sdk + ceph s3-tests in docker, SURVEY.md §4; this
build exercises the same surfaces — bucket CRUD, object CRUD, listing with
prefix/delimiter, multipart, tagging, multi-delete, SigV4 auth — in pytest
with a minimal hand-rolled SigV4 signer)."""

import hashlib
import hmac
import socket
import time
import urllib.parse
import xml.etree.ElementTree as ET

import pytest
import requests

from seaweedfs_tpu.pb import rpc
from seaweedfs_tpu.s3api.auth import Identity
from seaweedfs_tpu.s3api.server import S3Server
from seaweedfs_tpu.server.filer import FilerServer
from seaweedfs_tpu.server.master import MasterServer
from seaweedfs_tpu.server.volume import VolumeServer

NS = "{http://s3.amazonaws.com/doc/2006-03-01/}"


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


@pytest.fixture(scope="module")
def stack(tmp_path_factory):
    mport = _free_port()
    master = MasterServer(ip="localhost", port=mport, volume_size_limit_mb=64)
    master.start(vacuum_interval=3600)
    vsrv = VolumeServer(directories=[str(tmp_path_factory.mktemp("vol"))],
                        master=f"localhost:{mport}", ip="localhost",
                        port=_free_port(), pulse_seconds=1)
    vsrv.start()
    fsrv = FilerServer(ip="localhost", port=_free_port(),
                       master=f"localhost:{mport}", chunk_size=32 * 1024)
    fsrv.start()
    s3 = S3Server(port=_free_port(), filer=fsrv.address)
    s3.start()
    s3_auth = S3Server(port=_free_port(), filer=fsrv.address,
                       identities=[Identity("admin", "AKID123", "SECRET456")])
    s3_auth.start()
    deadline = time.time() + 10
    while time.time() < deadline and not master.topo.nodes:
        time.sleep(0.05)
    yield master, fsrv, s3, s3_auth
    s3_auth.stop()
    s3.stop()
    fsrv.stop()
    vsrv.stop()
    master.stop()
    rpc.reset_channels()


def test_bucket_and_object_crud(stack):
    *_, s3, _ = stack
    base = f"http://localhost:{s3.port}"
    assert requests.put(f"{base}/mybucket", timeout=30).status_code == 200
    # list buckets
    r = requests.get(base, timeout=30)
    assert "mybucket" in r.text
    # put/get/head/delete object
    body = b"hello s3 world" * 100
    r = requests.put(f"{base}/mybucket/dir/obj.txt", data=body, timeout=60,
                     headers={"Content-Type": "text/plain"})
    assert r.status_code == 200
    assert r.headers["ETag"]
    r = requests.get(f"{base}/mybucket/dir/obj.txt", timeout=60)
    assert r.status_code == 200 and r.content == body
    r = requests.head(f"{base}/mybucket/dir/obj.txt", timeout=30)
    assert r.status_code == 200
    assert int(r.headers["Content-Length"]) == len(body)
    # range
    r = requests.get(f"{base}/mybucket/dir/obj.txt", timeout=60,
                     headers={"Range": "bytes=5-14"})
    assert r.status_code == 206 and r.content == body[5:15]
    # 404s
    assert requests.get(f"{base}/mybucket/nope", timeout=30).status_code == 404
    assert requests.get(f"{base}/nobucket/x", timeout=30).status_code == 404
    # delete
    assert requests.delete(f"{base}/mybucket/dir/obj.txt",
                           timeout=30).status_code == 204
    assert requests.get(f"{base}/mybucket/dir/obj.txt",
                        timeout=30).status_code == 404


def test_listing_prefix_delimiter(stack):
    *_, s3, _ = stack
    base = f"http://localhost:{s3.port}"
    requests.put(f"{base}/listb", timeout=30)
    for key in ["a/1.txt", "a/2.txt", "a/sub/3.txt", "b/4.txt", "top.txt"]:
        requests.put(f"{base}/listb/{key}", data=b"x", timeout=30)

    r = requests.get(f"{base}/listb?list-type=2", timeout=30)
    root = ET.fromstring(r.content)
    keys = [c.find(f"{NS}Key").text for c in root.findall(f"{NS}Contents")]
    assert keys == ["a/1.txt", "a/2.txt", "a/sub/3.txt", "b/4.txt", "top.txt"]

    r = requests.get(f"{base}/listb?prefix=a/", timeout=30)
    root = ET.fromstring(r.content)
    keys = [c.find(f"{NS}Key").text for c in root.findall(f"{NS}Contents")]
    assert keys == ["a/1.txt", "a/2.txt", "a/sub/3.txt"]

    r = requests.get(f"{base}/listb?delimiter=/", timeout=30)
    root = ET.fromstring(r.content)
    keys = [c.find(f"{NS}Key").text for c in root.findall(f"{NS}Contents")]
    prefixes = [c.find(f"{NS}Prefix").text
                for c in root.findall(f"{NS}CommonPrefixes")]
    assert keys == ["top.txt"]
    assert prefixes == ["a/", "b/"]

    r = requests.get(f"{base}/listb?delimiter=/&prefix=a/", timeout=30)
    root = ET.fromstring(r.content)
    keys = [c.find(f"{NS}Key").text for c in root.findall(f"{NS}Contents")]
    prefixes = [c.find(f"{NS}Prefix").text
                for c in root.findall(f"{NS}CommonPrefixes")]
    assert keys == ["a/1.txt", "a/2.txt"]
    assert prefixes == ["a/sub/"]


def test_multipart_upload(stack):
    *_, s3, _ = stack
    base = f"http://localhost:{s3.port}"
    requests.put(f"{base}/mp", timeout=30)
    r = requests.post(f"{base}/mp/big.bin?uploads", timeout=30)
    upload_id = ET.fromstring(r.content).find(f"{NS}UploadId").text
    parts = [b"A" * 70_000, b"B" * 70_000, b"C" * 5_000]
    for i, p in enumerate(parts, start=1):
        r = requests.put(
            f"{base}/mp/big.bin?partNumber={i}&uploadId={upload_id}",
            data=p, timeout=60)
        assert r.status_code == 200
    # list parts
    r = requests.get(f"{base}/mp/big.bin?uploadId={upload_id}", timeout=30)
    nums = [int(p.find(f"{NS}PartNumber").text) for p in
            ET.fromstring(r.content).findall(f"{NS}Part")]
    assert nums == [1, 2, 3]
    r = requests.post(f"{base}/mp/big.bin?uploadId={upload_id}", timeout=60)
    assert r.status_code == 200
    got = requests.get(f"{base}/mp/big.bin", timeout=60)
    assert got.content == b"".join(parts)


def test_copy_multi_delete_tagging(stack):
    *_, s3, _ = stack
    base = f"http://localhost:{s3.port}"
    requests.put(f"{base}/cp", timeout=30)
    requests.put(f"{base}/cp/src.txt", data=b"copy me", timeout=30)
    r = requests.put(f"{base}/cp/dst.txt", timeout=30,
                     headers={"x-amz-copy-source": "/cp/src.txt"})
    assert r.status_code == 200
    assert requests.get(f"{base}/cp/dst.txt", timeout=30).content == b"copy me"

    # tagging
    tagxml = ("<Tagging><TagSet><Tag><Key>env</Key><Value>prod</Value></Tag>"
              "</TagSet></Tagging>")
    assert requests.put(f"{base}/cp/src.txt?tagging", data=tagxml,
                        timeout=30).status_code == 200
    r = requests.get(f"{base}/cp/src.txt?tagging", timeout=30)
    root = ET.fromstring(r.content)
    tags = {t.find(f"{NS}Key").text: t.find(f"{NS}Value").text
            for t in root.iter(f"{NS}Tag")}
    assert tags == {"env": "prod"}

    # multi-delete
    payload = ("<Delete><Object><Key>src.txt</Key></Object>"
               "<Object><Key>dst.txt</Key></Object></Delete>")
    r = requests.post(f"{base}/cp?delete", data=payload, timeout=30)
    assert r.status_code == 200
    assert r.text.count("<Deleted>") == 2
    assert requests.get(f"{base}/cp/src.txt", timeout=30).status_code == 404


# -- SigV4 ------------------------------------------------------------------

def _sign_v4(method: str, url: str, access: str, secret: str,
             body: bytes = b"", region: str = "us-east-1") -> dict:
    u = urllib.parse.urlparse(url)
    t = time.gmtime()
    amz_date = time.strftime("%Y%m%dT%H%M%SZ", t)
    date = time.strftime("%Y%m%d", t)
    payload_hash = hashlib.sha256(body).hexdigest()
    headers = {"host": u.netloc, "x-amz-date": amz_date,
               "x-amz-content-sha256": payload_hash}
    signed = sorted(headers)
    qs = urllib.parse.parse_qs(u.query, keep_blank_values=True)
    pairs = []
    for k in sorted(qs):
        for v in sorted(qs[k]):
            pairs.append(f"{urllib.parse.quote(k, safe='-_.~')}="
                         f"{urllib.parse.quote(v, safe='-_.~')}")
    creq = "\n".join([
        method, urllib.parse.quote(u.path or "/", safe="/-_.~"),
        "&".join(pairs),
        "".join(f"{h}:{headers[h]}\n" for h in signed),
        ";".join(signed), payload_hash])
    scope = f"{date}/{region}/s3/aws4_request"
    sts = "\n".join(["AWS4-HMAC-SHA256", amz_date, scope,
                     hashlib.sha256(creq.encode()).hexdigest()])
    k = hmac.new(("AWS4" + secret).encode(), date.encode(),
                 hashlib.sha256).digest()
    for part in (region, "s3", "aws4_request"):
        k = hmac.new(k, part.encode(), hashlib.sha256).digest()
    sig = hmac.new(k, sts.encode(), hashlib.sha256).hexdigest()
    headers["Authorization"] = (
        f"AWS4-HMAC-SHA256 Credential={access}/{scope}, "
        f"SignedHeaders={';'.join(signed)}, Signature={sig}")
    return headers


def test_sigv4_auth(stack):
    *_, s3_auth = stack
    base = f"http://localhost:{s3_auth.port}"
    # anonymous rejected
    assert requests.put(f"{base}/secure", timeout=30).status_code == 403
    # bad key rejected
    h = _sign_v4("PUT", f"{base}/secure", "WRONG", "nope")
    assert requests.put(f"{base}/secure", headers=h,
                        timeout=30).status_code == 403
    # bad secret rejected
    h = _sign_v4("PUT", f"{base}/secure", "AKID123", "badsecret")
    assert requests.put(f"{base}/secure", headers=h,
                        timeout=30).status_code == 403
    # good signature accepted, end to end
    h = _sign_v4("PUT", f"{base}/secure", "AKID123", "SECRET456")
    assert requests.put(f"{base}/secure", headers=h,
                        timeout=30).status_code == 200
    body = b"signed payload"
    h = _sign_v4("PUT", f"{base}/secure/f.bin", "AKID123", "SECRET456", body)
    assert requests.put(f"{base}/secure/f.bin", data=body, headers=h,
                        timeout=30).status_code == 200
    h = _sign_v4("GET", f"{base}/secure/f.bin", "AKID123", "SECRET456")
    r = requests.get(f"{base}/secure/f.bin", headers=h, timeout=30)
    assert r.status_code == 200 and r.content == body


def test_admin_plane_requires_admin_when_iam_on(stack):
    """/debug/traces and /status carry request-level data (object keys,
    internal addresses) — on an IAM-enabled gateway they must reject
    anonymous callers; the aggregate-only /metrics stays open."""
    *_, s3, s3_auth = stack
    base = f"http://localhost:{s3_auth.port}"
    for path in ("/debug/traces", "/status"):
        assert requests.get(base + path, timeout=30).status_code == 403
        h = _sign_v4("GET", base + path, "AKID123", "SECRET456")
        assert requests.get(base + path, headers=h,
                            timeout=30).status_code == 200
    assert requests.get(f"{base}/metrics", timeout=30).status_code == 200
    # IAM off (dev mode): admin plane stays open
    open_base = f"http://localhost:{s3.port}"
    assert requests.get(f"{open_base}/debug/traces",
                        timeout=30).status_code == 200
    assert requests.get(f"{open_base}/status", timeout=30).status_code == 200


def test_upload_part_copy(stack):
    """UploadPartCopy: parts sourced from an existing object with and
    without x-amz-copy-source-range (CopyObjectPartHandler parity)."""
    *_, s3, _ = stack
    base = f"http://localhost:{s3.port}"
    requests.put(f"{base}/upc", timeout=30)
    src = bytes(range(256)) * 500  # 128000 bytes
    requests.put(f"{base}/upc/source.bin", data=src, timeout=30)

    r = requests.post(f"{base}/upc/assembled.bin?uploads", timeout=30)
    upload_id = ET.fromstring(r.content).find(f"{NS}UploadId").text

    # part 1: byte range of the source
    r = requests.put(
        f"{base}/upc/assembled.bin?partNumber=1&uploadId={upload_id}",
        headers={"x-amz-copy-source": "/upc/source.bin",
                 "x-amz-copy-source-range": "bytes=0-69999"}, timeout=60)
    assert r.status_code == 200, r.text
    assert ET.fromstring(r.content).find(f"{NS}ETag") is not None
    # part 2: whole source
    r = requests.put(
        f"{base}/upc/assembled.bin?partNumber=2&uploadId={upload_id}",
        headers={"x-amz-copy-source": "/upc/source.bin"}, timeout=60)
    assert r.status_code == 200, r.text
    # invalid range -> 400 InvalidArgument (reference/AWS parity)
    r = requests.put(
        f"{base}/upc/assembled.bin?partNumber=3&uploadId={upload_id}",
        headers={"x-amz-copy-source": "/upc/source.bin",
                 "x-amz-copy-source-range": "bytes=999999-1000000"},
        timeout=60)
    assert r.status_code == 400 and b"InvalidArgument" in r.content, r.text

    r = requests.post(f"{base}/upc/assembled.bin?uploadId={upload_id}",
                      timeout=60)
    assert r.status_code == 200
    got = requests.get(f"{base}/upc/assembled.bin", timeout=60)
    assert got.content == src[:70000] + src
