"""Shell command tests over an in-process 3-node cluster: the full EC
lifecycle (`ec.encode` spread across servers, kill shards + `ec.rebuild`,
`ec.decode` back to a volume) plus volume.* and cluster.* commands —
the workflows of SURVEY.md §3.4/§3.5 driven exactly as an operator would."""

import io
import os
import socket
import time

import numpy as np
import pytest
import requests

from seaweedfs_tpu.operation import submit
from seaweedfs_tpu.pb import rpc
from seaweedfs_tpu.server.master import MasterServer
from seaweedfs_tpu.server.volume import VolumeServer
from seaweedfs_tpu.shell.env import CommandEnv
from seaweedfs_tpu.shell.registry import run_command
from seaweedfs_tpu.storage.ec_locate import Geometry
from seaweedfs_tpu.storage.file_id import parse_file_id
from seaweedfs_tpu.wdclient import MasterClient

TEST_GEO = Geometry(large_block=10000, small_block=100)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    mport = _free_port()
    master = MasterServer(ip="localhost", port=mport, volume_size_limit_mb=64)
    master.start(vacuum_interval=3600)
    volumes = []
    for i in range(3):
        vsrv = VolumeServer(
            directories=[str(tmp_path_factory.mktemp(f"vol{i}"))],
            master=f"localhost:{mport}", ip="localhost", port=_free_port(),
            ec_geometry=TEST_GEO, pulse_seconds=1,
        )
        vsrv.start()
        volumes.append(vsrv)
    deadline = time.time() + 10
    while time.time() < deadline and len(master.topo.nodes) < 3:
        time.sleep(0.05)
    assert len(master.topo.nodes) == 3
    yield master, volumes
    for v in volumes:
        v.stop()
    master.stop()
    rpc.reset_channels()


def _sh(env, line) -> str:
    out = io.StringIO()
    code = run_command(env, line, out)
    text = out.getvalue()
    assert code == 0, f"{line!r} failed:\n{text}"
    return text


def test_basic_commands(cluster):
    master, _ = cluster
    env = CommandEnv(master.address)
    assert "volume server" in _sh(env, "cluster.ps")
    assert "ok" in _sh(env, "cluster.check")
    assert "capacity" in _sh(env, "cluster.status")
    _sh(env, "collection.list")
    _sh(env, "volume.list")


def test_lock_required(cluster):
    master, _ = cluster
    env = CommandEnv(master.address)
    out = io.StringIO()
    assert run_command(env, "ec.encode -volumeId 1", out) == 1
    assert "lock" in out.getvalue()


def test_ec_full_lifecycle(cluster):
    master, volumes = cluster
    env = CommandEnv(master.address)
    _sh(env, "lock")

    rng = np.random.default_rng(1)
    blobs = {}
    for i in range(30):
        data = rng.integers(0, 256, size=int(rng.integers(500, 4000)),
                            dtype=np.uint8).tobytes()
        res = submit(master.address, data, filename=f"f{i}", collection="shec")
        blobs[res["fid"]] = data
    vid = parse_file_id(next(iter(blobs))).volume_id
    mine = {f: d for f, d in blobs.items()
            if parse_file_id(f).volume_id == vid}

    text = _sh(env, f"ec.encode -volumeId {vid} -collection shec")
    assert "spread" in text
    time.sleep(1.5)  # let heartbeats re-report

    # volume is gone; reads must go through EC shards (any server can serve)
    mc = MasterClient(master.address)
    for fid, data in mine.items():
        urls = mc.lookup_file_id(fid)
        r = requests.get(urls[0], timeout=30)
        assert r.status_code == 200, fid
        assert r.content == data

    # shards are spread across all three servers
    holders = {v.address: sorted(
        v.store.find_ec_volume(vid).shard_files.keys())
        for v in volumes if v.store.find_ec_volume(vid)}
    assert len(holders) == 3, holders
    assert sum(len(s) for s in holders.values()) == 14

    # destroy 3 shards (within RS(10,4)'s 4-loss tolerance), then rebuild
    victim = volumes[0]
    lost = holders[victim.address][:3]
    assert lost, "victim holds no shards?"
    ev = victim.store.find_ec_volume(vid)
    base = ev.base
    victim.store.unmount_ec_shards(vid)
    for sid in lost:
        os.remove(f"{base}.ec{sid:02d}")
    if len(holders[victim.address]) > len(lost):
        victim.store.mount_ec_shards(vid, "shec", [])
    victim.trigger_heartbeat()
    time.sleep(1.5)

    text = _sh(env, "ec.rebuild -collection shec")
    assert "rebuilt" in text
    time.sleep(1.5)

    # every file readable again, every shard present somewhere
    for fid, data in mine.items():
        urls = mc.lookup_file_id(fid)
        assert requests.get(urls[0], timeout=30).content == data
    present = set()
    for v in volumes:
        evv = v.store.find_ec_volume(vid)
        if evv:
            present |= set(evv.shard_files)
    assert present == set(range(14))

    # decode back to a plain volume (fresh client: EC-era location cache is
    # stale by design, like the reference's vidMap generations)
    text = _sh(env, f"ec.decode -volumeId {vid} -collection shec")
    assert "decoded" in text
    time.sleep(1.5)
    mc2 = MasterClient(master.address)
    for fid, data in mine.items():
        urls = mc2.lookup_file_id(fid)
        assert requests.get(urls[0], timeout=30).content == data

    _sh(env, "unlock")


def test_volume_balance_dry_run(cluster):
    master, _ = cluster
    env = CommandEnv(master.address)
    _sh(env, "lock")
    _sh(env, "volume.balance")
    _sh(env, "volume.fix.replication")
    _sh(env, "unlock")


def test_volume_check_disk(cluster):
    master, _ = cluster
    env = CommandEnv(master.address)
    # digest-riding check (ISSUE 4): summary counts integrity issues
    # (replica digest divergence + EC shard-copy divergence)
    assert "integrity issue(s) found" in _sh(env, "volume.check.disk")


def test_ec_encode_rack_aware_spread(tmp_path_factory):
    """Shard placement balances racks, not just nodes: a lone node in its
    own rack takes ~half the shards when the other rack has three nodes
    (the reference README's rack-aware EC placement)."""
    mport = _free_port()
    master = MasterServer(ip="localhost", port=mport, volume_size_limit_mb=64)
    master.start(vacuum_interval=3600)
    servers = []
    layout = [("rackA",), ("rackA",), ("rackA",), ("rackB",)]
    for i, (rack,) in enumerate(layout):
        vsrv = VolumeServer(
            directories=[str(tmp_path_factory.mktemp(f"rk{i}"))],
            master=f"localhost:{mport}", ip="localhost", port=_free_port(),
            ec_geometry=TEST_GEO, pulse_seconds=1, rack=rack,
            data_center="dc1")
        vsrv.start()
        servers.append(vsrv)
    try:
        deadline = time.time() + 10
        while time.time() < deadline and len(master.topo.nodes) < 4:
            time.sleep(0.05)
        rng = np.random.default_rng(5)
        fid = None
        for i in range(10):
            data = rng.integers(0, 256, 3000, dtype=np.uint8).tobytes()
            res = submit(master.address, data, filename=f"r{i}",
                         collection="rackec")
            fid = fid or res["fid"]
        vid = parse_file_id(fid).volume_id
        env = CommandEnv(master.address)
        out = io.StringIO()
        assert run_command(env, "lock", out) == 0
        assert run_command(
            env, f"ec.encode -volumeId {vid} -collection rackec", out) == 0, \
            out.getvalue()
        time.sleep(1.5)
        by_rack = {"rackA": 0, "rackB": 0}
        for s in servers:
            n = sum(len(ev.shard_files)
                    for loc in s.store.locations
                    for ev in loc.ec_volumes.values())
            by_rack[s.store.rack] += n
        assert by_rack["rackA"] + by_rack["rackB"] == 14, by_rack
        # rack-aware: B's one node carries ~half; count-balanced placement
        # would leave it with only ~3
        assert by_rack["rackB"] >= 6, by_rack
    finally:
        for s in servers:
            s.stop()
        master.stop()
        rpc.reset_channels()


def test_qos_status_command(cluster):
    """`qos.status` (ISSUE 8): one view of the QoS plane across the
    fleet — the master's grant ledger, each volume server's pressure +
    governor state — plain and -json forms."""
    import json

    master, volumes = cluster
    # the preceding test tears down its own cluster and calls
    # rpc.reset_channels(), which severs THIS cluster's heartbeat
    # streams too — the master defer-unregisters the nodes for ~1s
    # until the next pulse re-registers them; qos.status walks the
    # topology, so wait for the fleet to be whole again
    deadline = time.time() + 10
    while time.time() < deadline and len(master.topo.nodes) < len(volumes):
        time.sleep(0.05)
    assert len(master.topo.nodes) == len(volumes), master.topo.nodes
    # put some grant flow + a pressure report on record first
    master.qos_ledger.grant(volumes[0].address, "scrub", 1 << 20, 0.42)
    env = CommandEnv(master.address)
    text = _sh(env, "qos.status")
    assert "ledger" in text and "clusterBudgetMBps" in text
    assert volumes[0].address in text  # the reporting server is listed
    assert "pressure" in text and "governor" in text
    j = json.loads(_sh(env, "qos.status -json"))
    assert master.address in j and "ledger" in j[master.address]["qos"]
    led = j[master.address]["qos"]["ledger"]
    assert led["servers"][volumes[0].address]["pressure"] == 0.42
    # every volume server answers with its own governor section
    vols = [a for a, e in j.items() if e["kind"] == "volume"]
    assert len(vols) == len(volumes)
    for addr in vols:
        assert j[addr]["qos"]["governor"]["enabled"] is False
