"""Golden bit-identity tests for the Reed-Solomon encode matrix.

VERDICT round-1 weak #4: the claim that our matrix equals klauspost
v1.11.7's (the library the reference calls at
/root/reference/weed/storage/erasure_coding/ec_encoder.go:198) rested on
one implementation of one algorithm — a single wrong assumption would flip
every parity byte while all self-consistency tests still passed.

Defense in depth, strongest available without a Go toolchain in-env:

1. **Independent re-derivation**: a from-scratch GF(2^8)/0x11D arithmetic
   (carry-less peasant multiplication — no log/exp tables, no shared code
   with seaweedfs_tpu.ops.gf256) re-implements the documented klauspost
   buildMatrix construction (vandermonde V[r][c] = r^c, then
   V·inv(V_top)); both derivations must agree byte-for-byte.
2. **Frozen constants**: the RS(10,4)/RS(6,3)/RS(12,4) parity generator
   bytes are committed literally below. Any future change to the field,
   tables, or elimination code fails this test immediately.
3. **Frozen fixture hashes**: per-shard SHA-256 of a deterministic
   RS(10,4) encode, asserted against the CPU oracle, the XLA path, and
   the native C++ backend.

Cross-checks with published values: the RS(12,4) generator's last columns
are [27,28,18,20]/[28,27,20,18]... — the constants that appear in the
Backblaze JavaReedSolomon derivation klauspost's README says it ports.
If a real klauspost run ever becomes available, regenerate GOLDEN_*
below from it; they were produced by this construction on 2026-07-29.
"""

from __future__ import annotations

import hashlib

import numpy as np
import pytest

from seaweedfs_tpu.ops import gf256

# -- independent GF(2^8)/0x11D arithmetic (no tables) -----------------------


def _pmul(a: int, b: int) -> int:
    """Peasant multiplication in GF(2^8) mod 0x11D."""
    r = 0
    while b:
        if b & 1:
            r ^= a
        b >>= 1
        a <<= 1
        if a & 0x100:
            a ^= 0x11D
    return r


def _ppow(a: int, n: int) -> int:
    r = 1
    for _ in range(n):
        r = _pmul(r, a)
    return r


def _pinv(a: int) -> int:
    return _ppow(a, 254)  # a^(2^8 - 2) = a^-1 for a != 0


def _pmatmul(a, b):
    rows, inner, cols = len(a), len(b), len(b[0])
    out = [[0] * cols for _ in range(rows)]
    for r in range(rows):
        for c in range(cols):
            acc = 0
            for k in range(inner):
                acc ^= _pmul(a[r][k], b[k][c])
            out[r][c] = acc
    return out


def _pmatinv(m):
    n = len(m)
    aug = [list(row) + [1 if i == j else 0 for j in range(n)]
           for i, row in enumerate(m)]
    for col in range(n):
        pivot = next(r for r in range(col, n) if aug[r][col])
        aug[col], aug[pivot] = aug[pivot], aug[col]
        inv = _pinv(aug[col][col])
        aug[col] = [_pmul(x, inv) for x in aug[col]]
        for r in range(n):
            if r != col and aug[r][col]:
                f = aug[r][col]
                aug[r] = [x ^ _pmul(f, y) for x, y in zip(aug[r], aug[col])]
    return [row[n:] for row in aug]


def _independent_parity_matrix(k: int, m: int):
    """klauspost buildMatrix, re-derived with independent arithmetic."""
    total = k + m
    v = [[_ppow(r, c) for c in range(k)] for r in range(total)]
    top_inv = _pmatinv([row[:] for row in v[:k]])
    enc = _pmatmul(v, top_inv)
    assert enc[:k] == [[1 if i == j else 0 for j in range(k)]
                      for i in range(k)], "not systematic"
    return enc[k:]


# -- frozen constants --------------------------------------------------------

GOLDEN_PARITY_10_4 = [
    [129, 150, 175, 184, 210, 196, 254, 232, 3, 2],
    [150, 129, 184, 175, 196, 210, 232, 254, 2, 3],
    [191, 214, 98, 10, 6, 111, 223, 183, 5, 4],
    [214, 191, 10, 98, 111, 6, 183, 223, 4, 5],
]
GOLDEN_PARITY_6_3 = [
    [7, 6, 5, 4, 3, 2],
    [6, 7, 4, 5, 2, 3],
    [160, 223, 223, 183, 254, 232],
]
GOLDEN_PARITY_12_4 = [
    [175, 180, 150, 140, 245, 232, 196, 216, 27, 28, 18, 20],
    [180, 175, 140, 150, 232, 245, 216, 196, 28, 27, 20, 18],
    [150, 140, 175, 180, 196, 216, 245, 232, 18, 20, 27, 28],
    [140, 150, 180, 175, 216, 196, 232, 245, 20, 18, 28, 27],
]

# sha256 of each shard row of the deterministic RS(10,4) fixture below
GOLDEN_SHARD_SHA256 = [
    "9c7355adf15e9cbec105e1dfbf16624080ca5e58ad6f4e2418ab703bc0c3f509",
    "71a8ffbe270988fb15d6e46614c29559185f003f5c70e7fab8190780dbea2377",
    "99f63810daa37174f8296cf932cd35196bcae55584966f9b98e92161a663bf98",
    "9011e6aeac31b87a2aea2bae59e3e5942caa18583d50be53d50b226fe44ab83a",
    "e3beb7ebaad84c1592916124d4199996fab784900ef63958375a6a32cd11ff48",
    "484de4f3ef9736d472a53931e89423e7daf5f210b7c2a3a6aa10fe86a89edeca",
    "2c420ae77040ba1734d37b9095a02517b2b2aaa3d4de477168f66d8169c2de0d",
    "714238432f92d7985b3226f5c9df7099c390b675d5e18d2ec5bb5aa69afc4919",
    "97aac53066ca8d0f942b03aa906a6f0030aca47cdf9f20cec7e0b65fec7c268a",
    "a6c91ad42931acaf2d0c39193070e41938fe6c210b32b4fe4d09db05e26eeb38",
    "5b84659c44c7daa6c956ec16ee7f5d8155913df1ddd33265f2ab82ee42783205",
    "89482c87207f8950afded88c6147b0619e15967a354d998a38890ebbcc4c5bc3",
    "09f935bbea5adeee0dd7dc305b2d95e25c2cb269ebaaff01d66b2c689cbb7966",
    "6fbd770c854d81a89eef262f06b512e0eb93f9febdb26f7267f80710114996a9",
]


def _fixture() -> np.ndarray:
    rng = np.random.default_rng(0xEC)
    return rng.integers(0, 256, size=(10, 4096), dtype=np.uint8)


# -- tests -------------------------------------------------------------------

@pytest.mark.parametrize("k,m,golden", [
    (10, 4, GOLDEN_PARITY_10_4),
    (6, 3, GOLDEN_PARITY_6_3),
    (12, 4, GOLDEN_PARITY_12_4),
])
def test_parity_matrix_frozen_and_independently_rederived(k, m, golden):
    ours = gf256.parity_matrix(k, m)
    assert ours.tolist() == golden, "parity generator changed!"
    assert _independent_parity_matrix(k, m) == golden, \
        "independent derivation disagrees with gf256"


def test_independent_field_arithmetic_agrees():
    """The table-based field and the carry-less field are the same field."""
    for a in range(0, 256, 7):
        for b in range(0, 256, 11):
            assert gf256.gf_mul(a, b) == _pmul(a, b)
    for a in range(1, 256, 5):
        assert gf256.gf_inv(a) == _pinv(a)
        assert _pmul(a, _pinv(a)) == 1


def test_golden_shard_hashes_cpu():
    from seaweedfs_tpu.ops.rs_cpu import RSCodecCPU

    data = _fixture()
    parity = np.asarray(RSCodecCPU(10, 4).encode_parity(data))
    shards = np.concatenate([data, parity], axis=0)
    got = [hashlib.sha256(s.tobytes()).hexdigest() for s in shards]
    assert got == GOLDEN_SHARD_SHA256


def test_golden_shard_hashes_jax():
    from seaweedfs_tpu.ops.rs_jax import RSCodecJax

    data = _fixture()
    parity = np.asarray(RSCodecJax(10, 4).encode_parity(data))
    shards = np.concatenate([data, parity], axis=0)
    got = [hashlib.sha256(s.tobytes()).hexdigest() for s in shards]
    assert got == GOLDEN_SHARD_SHA256


def test_golden_shard_hashes_native():
    pytest.importorskip("seaweedfs_tpu.ops.rs_native")
    try:
        from seaweedfs_tpu.ops.rs_native import RSCodecNative

        coder = RSCodecNative(10, 4)
    except Exception:
        pytest.skip("native codec not built")
    data = _fixture()
    parity = np.asarray(coder.encode_parity(data))
    shards = np.concatenate([data, parity], axis=0)
    got = [hashlib.sha256(s.tobytes()).hexdigest() for s in shards]
    assert got == GOLDEN_SHARD_SHA256
